"""Elastic expert parallelism end-to-end (PR 19).

MoE as a first-class parallelism axis: the a2a dispatch modes against the
GSPMD einsum reference (layer-level fp32 is BITWISE — the explicit
exchange is a re-transport of the same math, not an approximation),
composition with the microbatch/ZeRO-1/overlap engines through
``build_sharded_train``, expert-axis param sharding, the grouped-dispatch
EP>1 guard, router-stats harvest, cache-key coverage of the MoE knobs,
and the zero-retrace steady state.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import trace_asserts

from dlrover_tpu.models.llama import moe_llama_config
from dlrover_tpu.models.moe import MoEMlp
from dlrover_tpu.models.transformer import TransformerLM
from dlrover_tpu.parallel import rules as lr
from dlrover_tpu.runtime.mesh import ParallelConfig, build_mesh
from dlrover_tpu.trainer import train_lib

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs the 8-device virtual mesh"
)

EP_MESH = ParallelConfig(expert=4, data=2)


def _moe_config(dispatch="einsum", num_experts=8, **kw):
    return moe_llama_config(
        "tiny", num_experts=num_experts, num_layers=2, max_seq_len=64,
        vocab_size=256, moe_dispatch=dispatch, **kw,
    )


def _batches(n, batch=16, seq=16, vocab=256, seed=0):
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        t = rng.integers(0, vocab, size=(batch, seq + 1), dtype=np.int32)
        out.append({"inputs": t[:, :-1], "targets": t[:, 1:]})
    return out


def _run(config, parallel=EP_MESH, n_steps=3, batch=16, seq=16, **build_kw):
    mesh = build_mesh(parallel)
    model = TransformerLM(config)
    opt = train_lib.make_optimizer("sgd", learning_rate=1e-2)
    train = train_lib.build_sharded_train(
        model, opt, mesh, lr.DEFAULT_RULES,
        global_batch_size=batch, seq_len=seq, **build_kw,
    )
    state = train.init(jax.random.PRNGKey(0))
    losses = []
    # Re-feed the same batch: loss must fall as the model memorizes it.
    b = train_lib.shard_batch(
        _batches(1, batch, seq, config.vocab_size)[0], train
    )
    for _ in range(n_steps):
        state, metrics = train.step(state, b)
        losses.append(float(metrics["loss"]))
    return losses, state, train


# -- layer-level dispatch parity ----------------------------------------------


def _layer_forward(dispatch, params, x, mesh, num_experts=8):
    layer = MoEMlp(
        num_experts=num_experts, d_ff=64, top_k=2, capacity_factor=2.0,
        activation="gelu", dtype=jnp.float32, param_dtype=jnp.float32,
        dispatch=dispatch,
    )
    with train_lib.use_mesh(mesh):
        out, aux = jax.jit(layer.apply)(params, x)
    return np.asarray(jax.device_get(out)), float(aux)


def test_a2a_layer_bitwise_matches_einsum():
    """fp32 layer forward: the explicit a2a exchange reproduces the GSPMD
    einsum dispatch BITWISE — same routing, same expert matmuls, same
    combine; only the transport changed."""
    mesh = build_mesh(EP_MESH)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(16, 8, 32)), jnp.float32)
    layer = MoEMlp(
        num_experts=8, d_ff=64, top_k=2, capacity_factor=2.0,
        activation="gelu", dtype=jnp.float32, param_dtype=jnp.float32,
        dispatch="einsum",
    )
    params = layer.init(jax.random.PRNGKey(0), x)
    out_e, aux_e = _layer_forward("einsum", params, x, mesh)
    out_a, aux_a = _layer_forward("a2a", params, x, mesh)
    np.testing.assert_array_equal(out_e, out_a)
    assert aux_e == aux_a


def test_a2a_int8_layer_close_to_einsum():
    """The int8 wire rounds the dispatch payload once per leg: close, not
    bitwise (block-quantized int8 + fp32 scales)."""
    mesh = build_mesh(EP_MESH)
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(16, 8, 32)), jnp.float32)
    layer = MoEMlp(
        num_experts=8, d_ff=64, top_k=2, capacity_factor=2.0,
        activation="gelu", dtype=jnp.float32, param_dtype=jnp.float32,
        dispatch="einsum",
    )
    params = layer.init(jax.random.PRNGKey(1), x)
    out_e, _ = _layer_forward("einsum", params, x, mesh)
    out_q, _ = _layer_forward("a2a_int8", params, x, mesh)
    np.testing.assert_allclose(out_e, out_q, rtol=0.05, atol=0.02)


def test_grouped_dispatch_raises_under_expert_axis():
    """grouped is per-device only: under EP>1 it must raise with a clear
    pointer, never silently compute with the wrong experts."""
    mesh = build_mesh(EP_MESH)
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.normal(size=(16, 8, 32)), jnp.float32)
    layer = MoEMlp(
        num_experts=8, d_ff=64, top_k=2, dtype=jnp.float32,
        param_dtype=jnp.float32, dispatch="grouped", gmm_block_rows=8,
    )
    params = layer.init(jax.random.PRNGKey(2), x)
    with train_lib.use_mesh(mesh):
        with pytest.raises(ValueError, match="grouped"):
            layer.apply(params, x)


# -- full-model training ------------------------------------------------------


# The einsum reference train is the baseline for every parity test;
# compile it once per process (tier-1 runs this file without xdist).
_EINSUM_LOSSES = None


def _einsum_ref_losses():
    global _EINSUM_LOSSES
    if _EINSUM_LOSSES is None:
        _EINSUM_LOSSES = _run(_moe_config("einsum"))[0]
    return _EINSUM_LOSSES


@pytest.mark.parametrize(
    "dispatch",
    ["a2a", pytest.param("a2a_int8", marks=pytest.mark.slow)],
)
def test_a2a_training_matches_einsum(dispatch):
    """End-to-end train losses under the explicit wire track the einsum
    reference inside the repo's cross-strategy tolerance (bf16 trunk
    reduction-order noise; the MoE layer itself is exact on fp32).  The
    int8 leg is slow-marked: the fast layer-level closeness test above
    is its tier-1 witness."""
    losses_e = _einsum_ref_losses()
    losses_a, _, _ = _run(_moe_config(dispatch))
    assert all(np.isfinite(losses_a))
    assert losses_a[-1] < losses_a[0]
    np.testing.assert_allclose(losses_e, losses_a, rtol=2e-2)


def test_moe_composes_with_accum_zero1_overlap():
    """The tentpole composition: MoE + grad-accum + ZeRO-1 + the overlap
    engine through one build_sharded_train — and on the same live state,
    expert weights land on the expert axis while the dense trunk (and
    the router, which every device must evaluate identically) does not."""
    losses, state, train = _run(
        _moe_config("a2a"),
        grad_accum=2, zero1=True, overlap=True, overlap_bucket_mb=0.2,
    )
    assert train.zero1 and train.overlap
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]

    flat, _ = jax.tree_util.tree_flatten_with_path(state.params)
    expert, dense = [], []
    for path, leaf in flat:
        name = jax.tree_util.keystr(path)
        is_expert = "moe" in name and "router" not in name
        (expert if is_expert else dense).append(
            (name, str(leaf.sharding.spec))
        )
    assert expert, "MoE model must have expert param leaves"
    assert all("expert" in spec for _, spec in expert), expert
    assert all("expert" not in spec for _, spec in dense), dense


@pytest.mark.slow
def test_moe_steady_state_no_retrace():
    """After the first compile, further steps (fresh batches) must not
    retrace: routing is data-dependent in values, not in shapes.
    Slow-marked: the committed MOE.json artifact test certifies
    retraces == 0 for both builds in tier-1."""
    config = _moe_config("a2a_int8")
    mesh = build_mesh(EP_MESH)
    model = TransformerLM(config)
    opt = train_lib.make_optimizer("sgd", learning_rate=1e-2)
    train = train_lib.build_sharded_train(
        model, opt, mesh, lr.DEFAULT_RULES,
        global_batch_size=16, seq_len=16,
    )
    state = train.init(jax.random.PRNGKey(0))
    batches = _batches(4)
    state, _ = train.step(
        state, train_lib.shard_batch(batches[0], train)
    )  # first trace paid
    with trace_asserts.assert_no_retrace("train_step"):
        for b in batches[1:]:
            state, metrics = train.step(state, train_lib.shard_batch(b, train))
    assert np.isfinite(float(metrics["loss"]))


@pytest.mark.slow
def test_moe_stats_harvest():
    """build_moe_stats_fn reads the sown router stats off the live state:
    [entropy, drop_fraction, load_0..load_{E-1}] with sane ranges.
    Slow-marked: the layer-level sow contract is witnessed in tier-1 by
    test_moe.py::test_router_stats_sown_as_intermediates."""
    config = _moe_config("a2a")
    mesh = build_mesh(EP_MESH)
    model = TransformerLM(config)
    opt = train_lib.make_optimizer("sgd", learning_rate=1e-2)
    train = train_lib.build_sharded_train(
        model, opt, mesh, lr.DEFAULT_RULES,
        global_batch_size=16, seq_len=16,
    )
    state = train.init(jax.random.PRNGKey(0))
    batch = train_lib.shard_batch(_batches(1)[0], train)
    state, _ = train.step(state, batch)
    stats_fn = train_lib.build_moe_stats_fn(model, train)
    vec = np.asarray(jax.device_get(stats_fn(state, batch)), np.float64)
    e = config.num_experts
    assert vec.shape == (2 + e,)
    entropy, drop, load = vec[0], vec[1], vec[2:]
    assert 0.0 <= entropy <= np.log(e) + 1e-6
    assert 0.0 <= drop <= 1.0
    assert np.all(load >= 0.0)
    np.testing.assert_allclose(load.sum(), 1.0, atol=1e-5)


def test_train_cache_key_covers_moe_knobs():
    """Single witness that MoE knobs shape the compiled-program name.

    Exhaustive knob-by-knob pinning now lives in tracelint's CKY001
    (cache-key coverage, tests/test_lint_gate.py): the rule resolves
    ``train_cache_key``'s signature and proves every program-shaping
    knob reaches the key, so hand-enumerating them here only duplicated
    that contract one knob behind."""
    from dlrover_tpu.runtime.compile_cache import train_cache_key

    def key(config):
        return train_cache_key(
            config, (2, 1, 1, 4, 1, 1),
            global_batch_size=16, seq_len=16,
        )

    base = _moe_config("a2a")
    assert key(base) == key(_moe_config("a2a"))
    assert key(base) != key(_moe_config("a2a_int8"))
