"""Quantization + grouped matmul kernel tests (interpret mode on CPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from dlrover_tpu.ops import grouped_matmul as gmm
from dlrover_tpu.ops import quantization as qz


def test_quantize_dequantize_roundtrip(rng):
    x = jnp.asarray(rng.normal(size=(33, 77)) * 5.0, jnp.float32)
    q, scales = qz.quantize(x)
    assert q.dtype == jnp.int8
    out = qz.dequantize(q, scales, x.shape)
    # absmax/127 per 256-block: error bounded by scale/2 per block
    err = np.abs(np.asarray(out) - np.asarray(x))
    bound = np.abs(np.asarray(x)).max() / 127.0
    assert err.max() <= bound + 1e-6


@pytest.mark.slow  # long optimizer tracking loop
def test_q8_adam_tracks_fp32_adam(rng):
    """Quantized Adam should follow full-precision Adam closely on a quadratic."""
    dim = 8192  # above min_quant_size -> quantized path
    target = jnp.asarray(rng.normal(size=(dim,)), jnp.float32)
    params_q = {"w": jnp.zeros(dim, jnp.float32), "b": jnp.zeros(8, jnp.float32)}
    params_f = {"w": jnp.zeros(dim, jnp.float32), "b": jnp.zeros(8, jnp.float32)}

    opt_q = qz.q8_adam(learning_rate=0.05)
    opt_f = optax.adam(0.05)
    s_q, s_f = opt_q.init(params_q), opt_f.init(params_f)

    def loss(p):
        return jnp.sum((p["w"] - target) ** 2) + jnp.sum(p["b"] ** 2)

    for _ in range(30):
        g_q = jax.grad(loss)(params_q)
        u_q, s_q = opt_q.update(g_q, s_q, params_q)
        params_q = optax.apply_updates(params_q, u_q)
        g_f = jax.grad(loss)(params_f)
        u_f, s_f = opt_f.update(g_f, s_f, params_f)
        params_f = optax.apply_updates(params_f, u_f)

    # quantized Adam must descend comparably to fp32 Adam (a few % per-step
    # state error is expected; divergence or stalls are not)
    loss_q, loss_f = float(loss(params_q)), float(loss(params_f))
    assert loss_q < 0.25 * dim, loss_q
    assert loss_q < 2.0 * loss_f + 1.0, (loss_q, loss_f)
    drift = jnp.abs(params_q["w"] - params_f["w"]).max()
    assert float(drift) < 0.25, float(drift)


def test_q8_adam_small_leaf_exact(rng):
    """Small leaves bypass quantization and match optax.adam exactly."""
    p = {"b": jnp.asarray(rng.normal(size=(16,)), jnp.float32)}
    g = {"b": jnp.asarray(rng.normal(size=(16,)), jnp.float32)}
    opt_q = qz.q8_adam(learning_rate=0.1)
    opt_f = optax.adam(0.1, eps_root=0.0)
    u_q, _ = opt_q.update(g, opt_q.init(p), p)
    u_f, _ = opt_f.update(g, opt_f.init(p), p)
    np.testing.assert_allclose(u_q["b"], u_f["b"], atol=1e-6, rtol=1e-5)


@pytest.mark.slow  # long optimizer tracking loop
def test_q4_adam_tracks_fp32_adam(rng):
    """4-bit moments: coarser than q8 but must still descend comparably
    (ref low_bit/functional.py q4 states)."""
    dim = 8192
    target = jnp.asarray(rng.normal(size=(dim,)), jnp.float32)
    params_q = {"w": jnp.zeros(dim, jnp.float32), "b": jnp.zeros(8, jnp.float32)}
    params_f = {"w": jnp.zeros(dim, jnp.float32), "b": jnp.zeros(8, jnp.float32)}

    opt_q = qz.q4_adam(learning_rate=0.05)
    opt_f = optax.adam(0.05)
    s_q, s_f = opt_q.init(params_q), opt_f.init(params_f)

    def loss(p):
        return jnp.sum((p["w"] - target) ** 2) + jnp.sum(p["b"] ** 2)

    # 4-bit moments converge with a slower transient than q8 (15 levels of
    # momentum); the contract is sustained descent to near-convergence,
    # not per-step tracking.
    for _ in range(100):
        g_q = jax.grad(loss)(params_q)
        u_q, s_q = opt_q.update(g_q, s_q, params_q)
        params_q = optax.apply_updates(params_q, u_q)
        g_f = jax.grad(loss)(params_f)
        u_f, s_f = opt_f.update(g_f, s_f, params_f)
        params_f = optax.apply_updates(params_f, u_f)

    loss_q = float(loss(params_q))
    assert loss_q < 0.02 * dim, loss_q
    assert np.isfinite(loss_q)


def test_q4_adam_state_is_1_25_bytes_per_param():
    """The point of q4: moment containers pack two values per byte and
    scales ride 8 lanes — ~1.25 bytes/param of optimizer state."""
    dim = 65536
    p = {"w": jnp.zeros(dim, jnp.float32)}
    opt = qz.q4_adam(learning_rate=0.1)
    state = opt.init(p)
    m = state.m["w"]
    total = (m.q.size * m.q.dtype.itemsize
             + m.scales.size * m.scales.dtype.itemsize) * 2  # m and v
    assert total / dim <= 1.3, total / dim
    # nibble round-trip sanity
    import numpy as np2
    vals = jnp.asarray(np2.arange(-7, 8).repeat(18)[:qz.BLOCK], jnp.int32)
    packed = qz._pack_nibbles_signed(vals[None, :])
    un = qz._unpack_nibbles_signed(packed)
    np.testing.assert_array_equal(un[0], np2.asarray(vals, np2.float32))


def test_q4_adam_small_leaf_exact(rng):
    p = {"b": jnp.asarray(rng.normal(size=(16,)), jnp.float32)}
    g = {"b": jnp.asarray(rng.normal(size=(16,)), jnp.float32)}
    opt_q = qz.q4_adam(learning_rate=0.1)
    opt_f = optax.adam(0.1, eps_root=0.0)
    u_q, _ = opt_q.update(g, opt_q.init(p), p)
    u_f, _ = opt_f.update(g, opt_f.init(p), p)
    # eps placement differs (we fold sqrt(1-b2) into the numerator; optax
    # rescales v before adding eps): agreement to ~1e-4 relative.
    np.testing.assert_allclose(u_q["b"], u_f["b"], atol=1e-5, rtol=1e-4)


def test_grouped_matmul_fwd(rng):
    e, k, m = 4, 64, 128
    sizes = jnp.asarray([256, 0, 128, 128], jnp.int32)
    n = int(sizes.sum())
    x = jnp.asarray(rng.normal(size=(n, k)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(e, k, m)) * 0.1, jnp.float32)
    out = gmm.grouped_matmul(x, w, sizes, block_rows=128)
    ref = gmm.grouped_matmul_ref(x, w, sizes)
    np.testing.assert_allclose(out, ref, atol=1e-4, rtol=1e-4)


def test_grouped_matmul_grads(rng):
    e, k, m = 3, 64, 64
    sizes = jnp.asarray([128, 256, 128], jnp.int32)
    n = int(sizes.sum())
    x = jnp.asarray(rng.normal(size=(n, k)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(e, k, m)) * 0.1, jnp.float32)

    def loss_kernel(x, w):
        return jnp.sum(gmm.grouped_matmul(x, w, sizes, block_rows=128) ** 2)

    def loss_ref(x, w):
        return jnp.sum(gmm.grouped_matmul_ref(x, w, sizes) ** 2)

    gx_k, gw_k = jax.grad(loss_kernel, argnums=(0, 1))(x, w)
    gx_r, gw_r = jax.grad(loss_ref, argnums=(0, 1))(x, w)
    np.testing.assert_allclose(gx_k, gx_r, atol=1e-3, rtol=1e-3)
    np.testing.assert_allclose(gw_k, gw_r, atol=1e-3, rtol=1e-3)


def test_grouped_matmul_empty_expert_grad(rng):
    """dw of an expert with zero rows must be exactly zero (not NaN)."""
    e, k, m = 3, 64, 64
    sizes = jnp.asarray([256, 0, 128], jnp.int32)
    n = int(sizes.sum())
    x = jnp.asarray(rng.normal(size=(n, k)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(e, k, m)), jnp.float32)
    gw = jax.grad(
        lambda w: jnp.sum(gmm.grouped_matmul(x, w, sizes, block_rows=128))
    )(w)
    assert np.all(np.isfinite(np.asarray(gw)))
    np.testing.assert_array_equal(np.asarray(gw[1]), 0.0)
