"""MoE gating unit tests (dlrover_tpu/models/moe.py)."""

import jax.numpy as jnp
import numpy as np

from dlrover_tpu.models.moe import top_k_gating


def test_top2_no_slot_collision():
    """First- and second-choice tokens must never share an (expert, slot)."""
    # Two tokens prefer expert 0 then 1; two prefer expert 1 then 0.
    logits = jnp.array(
        [[[2.0, 1.0], [2.0, 1.0], [1.0, 2.0], [1.0, 2.0]]]
    )  # [1, 4, 2]
    dispatch, combine, _ = top_k_gating(logits, k=2, capacity=4)
    occupancy = np.asarray(dispatch.sum(axis=1))  # [1, E, C]
    assert occupancy.max() <= 1.0 + 1e-6, occupancy
    # every token got both choices dispatched (capacity is ample)
    assert float(dispatch.sum()) == 8.0


def test_capacity_drops_overflow():
    logits = jnp.zeros((1, 8, 2))  # all tokens identical -> same expert order
    dispatch, _, _ = top_k_gating(logits, k=1, capacity=3)
    occupancy = np.asarray(dispatch.sum(axis=1))
    assert occupancy.max() <= 1.0 + 1e-6
    # only `capacity` tokens make it in
    assert float(dispatch.sum()) == 3.0


def test_combine_weights_normalized():
    rng = np.random.default_rng(0)
    logits = jnp.asarray(rng.normal(size=(2, 16, 4)).astype(np.float32))
    dispatch, combine, aux = top_k_gating(logits, k=2, capacity=16)
    # combine weights per token sum to ~1 where both choices kept
    token_mass = np.asarray(combine.sum(axis=(2, 3)))
    assert token_mass.max() <= 1.0 + 1e-5
    assert float(aux) > 0.0
