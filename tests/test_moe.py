"""MoE gating unit tests (dlrover_tpu/models/moe.py)."""

import jax
import jax.numpy as jnp
import numpy as np

from dlrover_tpu.models.moe import MoEMlp, _router_entropy, top_k_gating


def test_top2_no_slot_collision():
    """First- and second-choice tokens must never share an (expert, slot)."""
    # Two tokens prefer expert 0 then 1; two prefer expert 1 then 0.
    logits = jnp.array(
        [[[2.0, 1.0], [2.0, 1.0], [1.0, 2.0], [1.0, 2.0]]]
    )  # [1, 4, 2]
    dispatch, combine, _ = top_k_gating(logits, k=2, capacity=4)
    occupancy = np.asarray(dispatch.sum(axis=1))  # [1, E, C]
    assert occupancy.max() <= 1.0 + 1e-6, occupancy
    # every token got both choices dispatched (capacity is ample)
    assert float(dispatch.sum()) == 8.0


def test_capacity_drops_overflow():
    logits = jnp.zeros((1, 8, 2))  # all tokens identical -> same expert order
    dispatch, _, _ = top_k_gating(logits, k=1, capacity=3)
    occupancy = np.asarray(dispatch.sum(axis=1))
    assert occupancy.max() <= 1.0 + 1e-6
    # only `capacity` tokens make it in
    assert float(dispatch.sum()) == 3.0


def test_combine_weights_normalized():
    rng = np.random.default_rng(0)
    logits = jnp.asarray(rng.normal(size=(2, 16, 4)).astype(np.float32))
    dispatch, combine, aux = top_k_gating(logits, k=2, capacity=16)
    # combine weights per token sum to ~1 where both choices kept
    token_mass = np.asarray(combine.sum(axis=(2, 3)))
    assert token_mass.max() <= 1.0 + 1e-5
    assert float(aux) > 0.0


def test_router_entropy_bounds():
    """Uniform logits hit ln(E); a collapsed router hits ~0."""
    e = 8
    uniform = jnp.zeros((2, 16, e))
    assert float(_router_entropy(uniform)) == np.log(e).astype(np.float32)
    collapsed = jnp.zeros((2, 16, e)).at[..., 0].set(100.0)
    assert float(_router_entropy(collapsed)) < 1e-3


def _stats_layer(dispatch, capacity_factor=2.0):
    return MoEMlp(
        num_experts=4, d_ff=32, top_k=2, capacity_factor=capacity_factor,
        activation="gelu", dtype=jnp.float32, param_dtype=jnp.float32,
        dispatch=dispatch, gmm_block_rows=8,
    )


def test_router_stats_sown_as_intermediates():
    """Every dispatch path sows the ``moe_stats`` vector — entropy, drop
    fraction, per-expert load — but only when the caller asks for the
    intermediates collection (the compiled step never pays for it)."""
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.normal(size=(2, 16, 16)), jnp.float32)
    layer = _stats_layer("einsum")
    params = layer.init(jax.random.PRNGKey(3), x)
    (out, aux), inter = layer.apply(
        params, x, mutable=["intermediates"]
    )
    (vec,) = jax.tree_util.tree_leaves(inter)
    vec = np.asarray(vec, np.float64).ravel()
    assert vec.shape == (2 + 4,)
    entropy, drop, load = vec[0], vec[1], vec[2:]
    assert 0.0 <= entropy <= np.log(4) + 1e-6
    assert 0.0 <= drop <= 1.0
    np.testing.assert_allclose(load.sum(), 1.0, atol=1e-6)
    # The plain apply returns no intermediates: sow was a no-op.
    plain = layer.apply(params, x)
    assert isinstance(plain, tuple) and len(plain) == 2


def test_router_stats_grouped_is_dropless():
    """The grouped path books drop_fraction == 0 (dropless by design)
    even at a capacity factor that would drop most einsum dispatches."""
    rng = np.random.default_rng(4)
    x = jnp.asarray(rng.normal(size=(2, 16, 16)), jnp.float32)
    layer = _stats_layer("grouped", capacity_factor=0.25)
    params = layer.init(jax.random.PRNGKey(4), x)
    _, inter = layer.apply(params, x, mutable=["intermediates"])
    (vec,) = jax.tree_util.tree_leaves(inter)
    vec = np.asarray(vec, np.float64).ravel()
    assert vec[1] == 0.0  # dropless: nothing hit a capacity wall

    einsum_layer = _stats_layer("einsum", capacity_factor=0.25)
    _, inter = einsum_layer.apply(params, x, mutable=["intermediates"])
    (evec,) = jax.tree_util.tree_leaves(inter)
    assert float(np.ravel(evec)[1]) > 0.0  # the einsum path DID drop
