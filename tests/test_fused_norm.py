"""Fused Pallas LayerNorm backward (PROFILE.md r4 sink: 6.4 ms/layer of
LN-bwd fusions): one pass over (x, dy) produces dx + dscale + dbias.

Numerics-verified here (interpret mode on CPU); the on-chip speedup is
measured separately (PROFILE.md) and the model flag stays off until
priced.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlrover_tpu.ops.fused_norm import fused_layernorm

EPS = 1e-5


def _reference(x, scale, bias):
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x32 - mean), axis=-1, keepdims=True)
    y = (x32 - mean) * jax.lax.rsqrt(var + EPS) * scale.astype(jnp.float32)
    if bias is not None:
        y = y + bias.astype(jnp.float32)
    return y.astype(x.dtype)


@pytest.mark.parametrize("rows,d", [(64, 256), (100, 384)])  # ragged rows
def test_fused_ln_grads_match_reference(rows, d):
    key = jax.random.PRNGKey(0)
    kx, ks, kb, kd = jax.random.split(key, 4)
    x = jax.random.normal(kx, (rows, d), jnp.float32) * 2.0 + 0.5
    scale = jax.random.normal(ks, (d,), jnp.float32) * 0.3 + 1.0
    bias = jax.random.normal(kb, (d,), jnp.float32) * 0.1
    dy = jax.random.normal(kd, (rows, d), jnp.float32)

    def loss_ref(x, scale, bias):
        return jnp.sum(_reference(x, scale, bias) * dy)

    def loss_fused(x, scale, bias):
        return jnp.sum(
            fused_layernorm(x, scale, bias, EPS, 32) * dy
        )

    ref = jax.grad(loss_ref, argnums=(0, 1, 2))(x, scale, bias)
    got = jax.grad(loss_fused, argnums=(0, 1, 2))(x, scale, bias)
    for r, g, name in zip(ref, got, ("dx", "dscale", "dbias")):
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(r), rtol=2e-4, atol=2e-4,
            err_msg=name,
        )


def test_fused_ln_no_bias_and_batched_shape():
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 16, 128), jnp.float32)
    scale = jnp.ones((128,)) * 1.5
    dy = jax.random.normal(jax.random.PRNGKey(2), x.shape, jnp.float32)

    ref = jax.grad(
        lambda x, s: jnp.sum(_reference(x, s, None) * dy), argnums=(0, 1)
    )(x, scale)
    got = jax.grad(
        lambda x, s: jnp.sum(
            fused_layernorm(x, s, None, EPS, 16) * dy
        ),
        argnums=(0, 1),
    )(x, scale)
    for r, g in zip(ref, got):
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(r), rtol=2e-4, atol=2e-4
        )


def test_fused_ln_forward_matches_and_bf16_roundtrip():
    x = (
        jax.random.normal(jax.random.PRNGKey(3), (32, 256), jnp.float32)
        .astype(jnp.bfloat16)
    )
    scale = jnp.ones((256,))
    bias = jnp.zeros((256,))
    y = fused_layernorm(x, scale, bias, EPS)
    assert y.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(y, np.float32),
        np.asarray(_reference(x, scale, bias), np.float32),
        rtol=2e-2, atol=2e-2,
    )


@pytest.mark.slow  # full train-step build, ~15s on the 1-core CI box
def test_model_flag_trains_with_fused_ln():
    """fused_ln=True end-to-end: grads flow, loss finite, and the grads
    match the unfused model's on the same params."""
    from dlrover_tpu.models.gpt2 import gpt2_config
    from dlrover_tpu.models.transformer import TransformerLM
    from dlrover_tpu.trainer import train_lib
    import flax.linen as nn

    tokens = jax.random.randint(jax.random.PRNGKey(4), (2, 16), 0, 64)
    targets = jax.random.randint(jax.random.PRNGKey(5), (2, 16), 0, 64)
    grads = {}
    for fused in (False, True):
        cfg = gpt2_config(
            "124m", num_layers=2, d_model=64, num_heads=2, vocab_size=64,
            max_seq_len=16, param_dtype=jnp.float32, fused_ln=fused,
        )
        model = TransformerLM(cfg)
        params = nn.meta.unbox(
            model.init(jax.random.PRNGKey(0), tokens)["params"]
        )

        def loss(p, model=model):
            logits, _ = model.apply({"params": p}, tokens)
            return train_lib.cross_entropy_loss(logits, targets)[0]

        grads[fused] = jax.grad(loss)(params)
    for a, b in zip(jax.tree.leaves(grads[False]), jax.tree.leaves(grads[True])):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-3, atol=5e-3
        )


def test_fused_rmsnorm_grads_match_reference():
    from dlrover_tpu.ops.fused_norm import fused_rmsnorm

    def rms_ref(x, scale):
        x32 = x.astype(jnp.float32)
        var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
        return (x32 * jax.lax.rsqrt(var + EPS)
                * scale.astype(jnp.float32)).astype(x.dtype)

    x = jax.random.normal(jax.random.PRNGKey(7), (50, 256), jnp.float32)
    scale = jax.random.normal(jax.random.PRNGKey(8), (256,)) * 0.2 + 1.0
    dy = jax.random.normal(jax.random.PRNGKey(9), x.shape, jnp.float32)

    ref = jax.grad(
        lambda x, s: jnp.sum(rms_ref(x, s) * dy), argnums=(0, 1)
    )(x, scale)
    got = jax.grad(
        lambda x, s: jnp.sum(fused_rmsnorm(x, s, EPS, 16) * dy),
        argnums=(0, 1),
    )(x, scale)
    for r, g, name in zip(ref, got, ("dx", "dscale")):
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(r), rtol=2e-4, atol=2e-4,
            err_msg=name,
        )


@pytest.mark.slow  # full llama train-step build, ~14s on the 1-core CI box
def test_llama_family_trains_with_fused_rmsnorm():
    from dlrover_tpu.models.llama import llama_config
    from dlrover_tpu.models.transformer import TransformerLM
    from dlrover_tpu.trainer import train_lib
    import dataclasses
    import flax.linen as nn

    tokens = jax.random.randint(jax.random.PRNGKey(4), (2, 16), 0, 64)
    targets = jax.random.randint(jax.random.PRNGKey(5), (2, 16), 0, 64)
    grads = {}
    for fused in (False, True):
        cfg = llama_config(
            "tiny", num_layers=2, vocab_size=64, max_seq_len=16,
        )
        cfg = dataclasses.replace(
            cfg, fused_ln=fused, param_dtype=jnp.float32
        )
        model = TransformerLM(cfg)
        params = nn.meta.unbox(
            model.init(jax.random.PRNGKey(0), tokens)["params"]
        )

        def loss(p, model=model):
            logits, _ = model.apply({"params": p}, tokens)
            return train_lib.cross_entropy_loss(logits, targets)[0]

        grads[fused] = jax.grad(loss)(params)
    for a, b in zip(
        jax.tree.leaves(grads[False]), jax.tree.leaves(grads[True])
    ):
        # bf16 activations: the kernel's f32 xhat recompute rounds one
        # ulp differently from the AD chain on a fraction of elements.
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-2, atol=2e-2
        )
