"""Shared helpers for asserting on ``train_lib.TRACE_COUNTS``.

Retrace regressions (the compile-cache and grad-accum invariants) are
asserted in several suites; going through one helper keeps the failure
message uniform and stops each test from poking the counter dict with its
own off-by-one bookkeeping.
"""

import contextlib

from dlrover_tpu.trainer import train_lib


def snapshot(*names):
    """Current trace counts for ``names`` (default: ``train_step``)."""
    names = names or ("train_step",)
    return {name: train_lib.trace_count(name) for name in names}


@contextlib.contextmanager
def assert_no_retrace(*names):
    """Assert the wrapped block triggers ZERO fresh traces of ``names``.

    Use after a warm-up step has already paid the first compilation::

        with trace_asserts.assert_no_retrace("train_step", "init"):
            trainer.fit(more_batches, max_steps=2)
    """
    before = snapshot(*names)
    yield before
    after = snapshot(*before)
    assert after == before, (
        f"unexpected retrace: before={before} after={after}"
    )
