"""Data pipeline tests: elastic sampler position, loader collation,
index-level shard acking (ref SURVEY.md §2.3 elastic sampler/dataloader)."""

import numpy as np

from dlrover_tpu.data.loader import (
    ElasticDataLoader,
    ElasticDistributedSampler,
    synthetic_lm_sample_fn,
)


def test_sampler_rank_partition_disjoint():
    samplers = [
        ElasticDistributedSampler(100, num_replicas=4, rank=r, shuffle=True)
        for r in range(4)
    ]
    seen = [list(s) for s in samplers]
    flat = sorted(sum(seen, []))
    assert flat == sorted(set(flat))  # disjoint
    assert len(flat) == 100


def test_sampler_resume_skew_completed_not_multiple_of_replicas():
    """Resume with ``completed % num_replicas != 0`` (a resize changed the
    world mid-epoch): ranks must cover EXACTLY the unconsumed suffix of
    the shuffled order — no double-consume, no skipped samples."""
    n, replicas, seed = 29, 3, 7
    for completed in (4, 7, 11):  # 4%3=1, 7%3=1, 11%3=2 — all skewed
        assert completed % replicas != 0
        samplers = [
            ElasticDistributedSampler(
                n, num_replicas=replicas, rank=r, shuffle=True, seed=seed
            )
            for r in range(replicas)
        ]
        for s in samplers:
            s.load_state_dict({"epoch": 0, "completed": completed})
        per_rank = [list(s) for s in samplers]
        flat = sum(per_rank, [])
        assert len(flat) == len(set(flat))  # no rank double-consumes
        order = np.random.default_rng(seed).permutation(n)
        remaining = sorted(int(x) for x in order[completed:])
        assert sorted(flat) == remaining    # nothing skipped, nothing extra


def test_sampler_checkpoint_resume():
    s = ElasticDistributedSampler(64, num_replicas=2, rank=0, shuffle=True)
    full = list(s)
    s.record_batch(16)  # 8 per rank consumed (global batch 16)
    state = s.state_dict()

    # resume on a *different* world size: 4 replicas now
    s2 = ElasticDistributedSampler(64, num_replicas=4, rank=0, shuffle=True)
    s2.load_state_dict(state)
    resumed = list(s2)
    # the first 16 global samples are skipped on resume
    order = np.random.default_rng(0).permutation(64)
    consumed = set(int(x) for x in order[:16])
    assert not (set(resumed) & consumed)


def test_sampler_logical_shard_keying_disjoint_cover():
    """Virtual-mesh keying: positions belong to LOGICAL shards (j % L),
    members own the shards that fold onto them (s % P == rank).  A
    2-member world over 4 logical shards covers exactly what the
    4-member world covers, member r taking the union of logical shards
    r and r+2 — the same strided fold the trainer's VirtualMesh uses."""
    n, L, seed = 48, 4, 5
    folded = [
        ElasticDistributedSampler(
            n, num_replicas=2, rank=r, shuffle=True, seed=seed,
            logical_world=L,
        )
        for r in range(2)
    ]
    assert folded[0].owned_logical_shards() == [0, 2]
    assert folded[1].owned_logical_shards() == [1, 3]
    legacy = [
        ElasticDistributedSampler(
            n, num_replicas=4, rank=r, shuffle=True, seed=seed
        )
        for r in range(4)
    ]
    per_member = [sorted(list(s)) for s in folded]
    # Disjoint and complete...
    flat = sum(per_member, [])
    assert sorted(flat) == sorted(set(flat))
    assert len(flat) == n
    # ...and each member consumes EXACTLY its logical shards' samples —
    # the samples ranks r and r+2 of the 4-world would have consumed.
    for r in range(2):
        want = sorted(list(legacy[r]) + list(legacy[r + 2]))
        assert per_member[r] == want


def test_sampler_grow_resume_2_to_4():
    """Grow-path resume: consume under a folded 2-member world (L=4),
    rebind the survivors and add two fresh members — the four-way
    continuation equals the never-resized 4-member run, per rank."""
    n, L, seed, consumed = 48, 4, 9, 16
    folded = [
        ElasticDistributedSampler(
            n, num_replicas=2, rank=r, shuffle=True, seed=seed,
            logical_world=L,
        )
        for r in range(2)
    ]
    for s in folded:
        s.record_batch(consumed)
    state = folded[0].state_dict()

    # Members 0/1 rebind in place; members 2/3 are fresh joiners that
    # load the same shard watermark.
    grown = []
    for r in range(4):
        if r < 2:
            folded[r].rebind_world(rank=r, num_replicas=4)
            grown.append(folded[r])
        else:
            s = ElasticDistributedSampler(
                n, num_replicas=4, rank=r, shuffle=True, seed=seed,
                logical_world=L,
            )
            s.load_state_dict(state)
            grown.append(s)

    reference = [
        ElasticDistributedSampler(
            n, num_replicas=4, rank=r, shuffle=True, seed=seed,
            logical_world=L,
        )
        for r in range(4)
    ]
    for s in reference:
        s.load_state_dict({"epoch": 0, "completed": consumed})

    for r in range(4):
        assert list(grown[r]) == list(reference[r]), f"rank {r} diverged"
    # And the union is exactly the unconsumed suffix of the epoch order.
    flat = sum((list(s) for s in reference), [])
    order = np.random.default_rng(seed).permutation(n)
    assert sorted(flat) == sorted(int(x) for x in order[consumed:])


def test_loader_collate_and_prefetch():
    loader = ElasticDataLoader(
        synthetic_lm_sample_fn(vocab_size=50, seq_len=16),
        batch_size=4,
        source=range(10),
        prefetch=2,
    )
    batches = list(loader)
    assert len(batches) == 2  # drop_last
    assert batches[0]["inputs"].shape == (4, 16)
    assert batches[0]["targets"].dtype == np.int32


class _FakeTaskMaster:
    """Minimal master double serving fixed-size shard tasks."""

    def __init__(self, num_shards, shard_size):
        self.tasks = [
            type("T", (), dict(
                task_id=i, start=i * shard_size, end=(i + 1) * shard_size,
                empty=False, epoch=0, dataset_name="d",
            ))()
            for i in range(num_shards)
        ]
        self.done = []

    def create_dataset(self, params):
        pass

    def get_task(self, name):
        if self.tasks:
            return self.tasks.pop(0)
        return type("T", (), dict(task_id=-1, empty=True))()

    def report_task(self, name, task_id, success):
        self.done.append(task_id)


def test_loader_acks_only_consumed_shards():
    """A shard must not be acked while its batch sits in the prefetch queue
    (crash would silently skip data); breaking early leaves shards unacked."""
    from dlrover_tpu.data.loader import ElasticDataLoader
    from dlrover_tpu.data.sharding_client import ShardingClient

    fake = _FakeTaskMaster(num_shards=4, shard_size=8)
    client = ShardingClient(fake, "d", create=False)
    loader = ElasticDataLoader(
        lambda i: {"x": np.asarray([i])}, batch_size=8,
        source=client, prefetch=2,
    )
    it = iter(loader)
    next(it)   # batch 0 handed out; shard 0 completes it but is NOT acked yet
    assert fake.done == []
    next(it)   # consumer came back: batch 0 was trained -> shard 0 acks
    assert fake.done == [0]
    it.close()  # abandon: shards 1..3 never acked (requeue via timeout)
    assert fake.done == [0]

    # full consumption acks everything
    fake2 = _FakeTaskMaster(num_shards=3, shard_size=8)
    client2 = ShardingClient(fake2, "d", create=False)
    loader2 = ElasticDataLoader(
        lambda i: {"x": np.asarray([i])}, batch_size=8,
        source=client2, prefetch=2,
    )
    assert len(list(loader2)) == 3
    assert sorted(fake2.done) == [0, 1, 2]


def test_index_sharding_client_acks_batches():
    class FakeMaster:
        def __init__(self):
            self.tasks = [
                type("T", (), dict(task_id=i, start=i * 8, end=(i + 1) * 8,
                                   empty=False, epoch=0,
                                   dataset_name="d"))()
                for i in range(3)
            ]
            self.done = []

        def create_dataset(self, params):
            pass

        def get_task(self, name):
            if self.tasks:
                return self.tasks.pop(0)
            return type("T", (), dict(task_id=-1, empty=True))()

        def report_task(self, name, task_id, success):
            self.done.append(task_id)

    from dlrover_tpu.data.sharding_client import IndexShardingClient

    fake = FakeMaster()
    client = IndexShardingClient(fake, "d", create=False)
    indices = [client.fetch_sample_index() for _ in range(12)]
    assert indices == list(range(12))
    client.report_batch_done(8)
    assert fake.done == [0]  # first shard fully consumed
    client.report_batch_done(8)  # 16 consumed -> shard 1 done
    assert fake.done == [0, 1]


def test_sharding_client_honors_record_indices():
    """Master-side sample shuffling must survive the production data path
    (code-review r5: consumers previously expanded range(start, end) and
    silently dropped the permutation)."""
    from dlrover_tpu.data.sharding_client import task_sample_indices
    from dlrover_tpu.master.messages import ShardTask

    shuffled = ShardTask(task_id=1, start=0, end=4,
                         record_indices=[9, 2, 7, 0])
    assert list(task_sample_indices(shuffled)) == [9, 2, 7, 0]
    plain = ShardTask(task_id=2, start=4, end=7)
    assert list(task_sample_indices(plain)) == [4, 5, 6]
