"""Declarative ElasticJobSpec tier (VERDICT r4 missing #5).

Ref ``dlrover/go/operator/api/v1alpha1/elasticjob_types.go:29-127``: the
job is declared in a versioned spec that drives the master; CLI flags are
overrides.  Includes an end-to-end CLI launch from a spec file.
"""

import os
import subprocess
import sys

import pytest

from dlrover_tpu.common.job_spec import (
    ElasticJobSpec,
    JobSpecError,
    load_job_spec,
    spec_from_dict,
)
from dlrover_tpu.run import _parse_args

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

TOML_SPEC = """
api_version = "dlrover-tpu/v1"
job_name = "llm-pretrain"

[nodes]
min = 2
max = 8
unit = 2

[accelerator]
type = "v5litepod-16"
preemptible = true

[master]
heartbeat_timeout = 45.0
hang_threshold = 600.0

[brain]
uplift_threshold = 1.2
stale_after_s = 1800.0

[checkpoint]
dir = "/ckpt"
every = 50

[trainer]
command = ["python", "train.py", "--steps", "100"]
max_restarts = 5
env = {DATA_DIR = "/data"}
"""


def test_toml_spec_roundtrip(tmp_path):
    path = tmp_path / "job.toml"
    path.write_text(TOML_SPEC)
    spec = load_job_spec(str(path))
    assert spec.job_name == "llm-pretrain"
    assert (spec.nodes.min, spec.nodes.max, spec.nodes.unit) == (2, 8, 2)
    assert spec.accelerator.type == "v5litepod-16"
    assert spec.accelerator.preemptible
    assert spec.master.heartbeat_timeout == 45.0
    assert spec.brain.uplift_threshold == 1.2
    assert spec.checkpoint.dir == "/ckpt"
    assert spec.trainer.command[:2] == ["python", "train.py"]
    assert spec.trainer.env == {"DATA_DIR": "/data"}
    assert spec.trainer.max_restarts == 5


def test_yaml_and_json_formats(tmp_path):
    yaml_path = tmp_path / "job.yaml"
    yaml_path.write_text(
        "api_version: dlrover-tpu/v1\n"
        "job_name: yjob\n"
        "nodes: {min: 1, max: 4}\n"
        "trainer: {command: [python, t.py]}\n"
    )
    spec = load_job_spec(str(yaml_path))
    assert spec.job_name == "yjob" and spec.nodes.max == 4

    json_path = tmp_path / "job.json"
    json_path.write_text(
        '{"api_version": "dlrover-tpu/v1", "job_name": "jjob",'
        ' "nodes": {"min": 1, "max": 2}}'
    )
    assert load_job_spec(str(json_path)).job_name == "jjob"

    with pytest.raises(JobSpecError, match="unsupported spec format"):
        bad = tmp_path / "job.ini"
        bad.write_text("x")
        load_job_spec(str(bad))


def test_unknown_keys_and_versions_rejected():
    with pytest.raises(JobSpecError, match="unknown key"):
        spec_from_dict({"nodes": {"mln": 2}})  # typo'd knob must not
    with pytest.raises(JobSpecError, match="unknown top-level"):
        spec_from_dict({"nodez": {}})
    with pytest.raises(JobSpecError, match="api_version"):
        spec_from_dict({"api_version": "dlrover-tpu/v0"})
    with pytest.raises(JobSpecError, match="min <= max"):
        spec_from_dict({"nodes": {"min": 4, "max": 2}})
    with pytest.raises(JobSpecError, match="unit"):
        spec_from_dict({"nodes": {"min": 1, "max": 4, "unit": 3}})


def test_cli_flags_override_spec(tmp_path):
    path = tmp_path / "job.toml"
    path.write_text(TOML_SPEC)
    # Spec alone: values flow through, command comes from the spec.
    args = _parse_args(["--job-spec", str(path)])
    assert args.nnodes == "2:8"
    assert args.node_unit == 2
    assert args.max_restarts == 5
    assert args.checkpoint_dir == "/ckpt"
    assert args.command == ["python", "train.py", "--steps", "100"]
    # Explicit flags (and an explicit command) win over the spec.
    args = _parse_args([
        "--job-spec", str(path), "--nnodes", "1:2", "--max-restarts", "1",
        "--", "python", "other.py",
    ])
    assert args.nnodes == "1:2"
    assert args.max_restarts == 1
    assert args.node_unit == 2  # untouched flag keeps the spec value
    assert args.command == ["python", "other.py"]


def test_defaults_are_valid():
    assert ElasticJobSpec().validate().nodes.max == 1


@pytest.mark.slow
def test_e2e_cli_launch_from_spec_file(tmp_path, cpu_child_env):
    """The full thing: write a spec, launch with --job-spec only (no
    trainer command on the CLI), training completes and checkpoints."""
    ckpt_dir = str(tmp_path / "ckpt")
    trainer = os.path.join(REPO, "examples", "train_lm.py")
    spec_path = tmp_path / "job.toml"
    spec_path.write_text(f"""
api_version = "dlrover-tpu/v1"
job_name = "spec-e2e"

[nodes]
min = 1
max = 1

[checkpoint]
dir = "{ckpt_dir}"

[trainer]
command = [
    "{sys.executable}", "{trainer}",
    "--steps", "6", "--ckpt-every", "3",
    "--checkpoint-dir", "{ckpt_dir}",
    "--layers", "1", "--d-model", "64", "--heads", "2",
    "--seq-len", "64", "--batch-size", "4",
]
monitor_interval = 1.0
env = {{SPEC_E2E_MARKER = "1"}}
""")
    env = dict(cpu_child_env)
    env.update({
        "DLROVER_TPU_SOCKET_DIR": str(tmp_path / "socks"),
        "DLROVER_TPU_JOB": f"spec{os.getpid()}",
        "PYTHONPATH": REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
    })
    env.pop("XLA_FLAGS", None)
    result = subprocess.run(
        [sys.executable, "-m", "dlrover_tpu.run", "--standalone",
         "--job-spec", str(spec_path)],
        env=env, timeout=600, capture_output=True, text=True,
    )
    assert result.returncode == 0, result.stderr[-3000:]
    from dlrover_tpu.common.storage import (
        CheckpointDirLayout,
        PosixDiskStorage,
    )

    assert CheckpointDirLayout(ckpt_dir).latest_step(PosixDiskStorage()) == 6


def test_env_values_coerced_to_strings(tmp_path):
    path = tmp_path / "j.toml"
    path.write_text(
        'api_version = "dlrover-tpu/v1"\njob_name = "j"\n'
        '[trainer]\nenv = {OMP_NUM_THREADS = 4, FAST = true, NAME = "x"}\n'
    )
    spec = load_job_spec(str(path))
    assert spec.trainer.env == {
        "OMP_NUM_THREADS": "4", "FAST": "1", "NAME": "x"
    }
    with pytest.raises(JobSpecError, match="env.BAD must be a scalar"):
        spec_from_dict({
            "job_name": "j", "trainer": {"env": {"BAD": [1, 2]}}
        })


def test_master_only_cloud_wiring(tmp_path):
    """--master-only --cloud builds the master with the spec's brain
    thresholds and a launcher made from [accelerator]+job_name (the
    code-review r5 finding: those sections must actually be consumed)."""
    import time as _time

    from dlrover_tpu.master.cloud_launcher import (
        CloudNodeLauncher,
        FakeTpuVmClient,
    )
    from dlrover_tpu.run import build_cluster_master

    path = tmp_path / "job.toml"
    path.write_text("""
api_version = "dlrover-tpu/v1"
job_name = "cloudjob"

[nodes]
min = 1
max = 2

[accelerator]
type = "v5litepod-16"
runtime_version = "rt-x"

[brain]
patience = 7
stale_after_s = 123.0
""")
    seen = {}

    def factory(spec, master_addr):
        seen["accel"] = spec.accelerator.type
        seen["addr"] = master_addr
        return CloudNodeLauncher(
            FakeTpuVmClient(), job_name=spec.job_name,
            master_addr=master_addr,
            accelerator_type=spec.accelerator.type,
            runtime_version=spec.accelerator.runtime_version,
        )

    args = _parse_args(["--master-only", "--cloud", "--job-spec", str(path)])
    master, launcher = build_cluster_master(args, launcher_factory=factory)
    try:
        assert seen["accel"] == "v5litepod-16"
        assert ":" in seen["addr"]
        # Brain thresholds flowed from the spec into the optimizer.
        assert master.auto_scaler.optimizer.patience == 7
        assert master.auto_scaler.optimizer.stale_after_s == 123.0
        master.start()
        master.bootstrap_nodes()
        deadline = _time.monotonic() + 5
        client = launcher.client
        while _time.monotonic() < deadline and (
            len(client.instances) < 2
        ):
            _time.sleep(0.05)
        assert sorted(client.instances) == [
            "cloudjob-worker-0", "cloudjob-worker-1"
        ]
        meta = client.get_node("cloudjob-worker-0")["metadata"]
        assert meta["dlrover-master-addr"] == seen["addr"]
        assert client.get_node("cloudjob-worker-0")[
            "accelerator_type"
        ] == "v5litepod-16"
    finally:
        master.stop()
        launcher.shutdown()


def test_job_phase_lifecycle_and_teardown(tmp_path):
    """Operator lifecycle (ref elasticjob_controller.go status.phase):
    pending -> running -> succeeded, and teardown deletes the VMs."""
    from dlrover_tpu.master.cloud_launcher import (
        CloudNodeLauncher,
        FakeTpuVmClient,
    )
    from dlrover_tpu.master.job_master import JobMaster

    client = FakeTpuVmClient()
    launcher = CloudNodeLauncher(client, job_name="ph")
    master = JobMaster(num_nodes=2, launcher=launcher,
                       heartbeat_timeout=3600.0)
    try:
        assert master.job_phase() == "pending"
        master.bootstrap_nodes()
        assert master.job_phase() == "pending"  # VMs up, no heartbeats
        master.node_manager.report_event(0, "started")
        assert master.job_phase() == "running"
        master.node_manager.report_event(1, "started")
        master.node_manager.report_event(0, "succeeded")
        assert master.job_phase() == "running"  # one node still going
        master.node_manager.report_event(1, "succeeded")
        assert master.job_phase() == "succeeded"

        import time as _t
        deadline = _t.monotonic() + 5
        while _t.monotonic() < deadline and len(client.instances) < 2:
            _t.sleep(0.05)
        master.teardown_nodes()
        assert all(
            i["state"] == "TERMINATED" for i in client.instances.values()
        )
    finally:
        master.stop()
        launcher.shutdown()


def test_job_phase_failed():
    from dlrover_tpu.master.job_master import JobMaster

    master = JobMaster(num_nodes=1, max_relaunches=0,
                       heartbeat_timeout=3600.0)
    try:
        master.node_manager.report_event(0, "started")
        master.node_manager.report_event(0, "failed", "boom")
        assert master.job_phase() == "failed"
    finally:
        master.stop()


def test_typed_pools_and_migration():
    """Typed node pools (ref PS/worker typed managers, ps.py:369 /
    worker.py:307): a coworker pool is bootstrapped and repaired beside
    the trainers but stays out of the scaler's sizing, and a pool node
    can MIGRATE — replacement launched, original drained and retired
    once the replacement reports in."""
    from dlrover_tpu.master.cloud_launcher import (
        CloudNodeLauncher,
        FakeTpuVmClient,
    )
    from dlrover_tpu.master.job_master import JobMaster
    from dlrover_tpu.master.node_manager import NodeManager

    base = NodeManager.POOL_ID_STRIDE
    client = FakeTpuVmClient()
    launcher = CloudNodeLauncher(client, job_name="tp")
    master = JobMaster(
        num_nodes=2, min_nodes=1, launcher=launcher, auto_scale=True,
        heartbeat_timeout=3600.0, pools={"coworker": 2},
    )
    try:
        nm = master.node_manager
        assert nm.pool_of(0) == "worker"
        assert nm.pool_of(base) == "coworker"
        assert sorted(nm.statuses(pool="coworker")) == [base, base + 1]
        master.bootstrap_nodes()
        import time as _t
        deadline = _t.monotonic() + 5
        while _t.monotonic() < deadline and len(client.instances) < 4:
            _t.sleep(0.05)
        # All four hosts (2 trainers + 2 coworkers) were created.
        assert len(client.instances) == 4

        # The scaler sizes the WORKER pool only.
        for n in (0, 1):
            nm.report_event(n, "started")
        master.auto_scaler.set_target(1, reason="test")
        plan = master.auto_scaler.step()
        assert plan is not None and plan.delete == [1]  # never a coworker

        # Migration: replacement comes up, original drains then retires.
        nm.report_event(base, "started")
        new_id = nm.migrate(base)
        assert new_id == base + 2
        assert nm.statuses()[base] == "preempting"  # still serving
        deadline = _t.monotonic() + 5
        while _t.monotonic() < deadline and (
            f"tp-worker-{new_id}" not in client.instances
        ):
            _t.sleep(0.05)
        nm.report_event(new_id, "started")   # replacement checks in
        assert nm.statuses()[base] == "succeeded"  # original retired
        assert "tp-worker-10000" in client.delete_calls
    finally:
        master.stop()
        launcher.shutdown()


def test_spec_coworker_pool_flows_to_master(tmp_path):
    from dlrover_tpu.run import _master_kwargs_from_spec

    path = tmp_path / "j.toml"
    path.write_text(
        'api_version = "dlrover-tpu/v1"\njob_name = "j"\n'
        "[nodes]\nmin = 1\nmax = 2\ncoworkers = 3\n"
    )
    kwargs = _master_kwargs_from_spec(load_job_spec(str(path)))
    assert kwargs["pools"] == {"coworker": 3}


def test_pool_node_heartbeat_death_repaired_under_scaler():
    """Code-review r5: the scaler is worker-pool-scoped, so a coworker
    host dying by heartbeat timeout must be relaunched by the master's
    death handler — not silently left DEAD forever."""
    from dlrover_tpu.master.job_master import JobMaster
    from dlrover_tpu.master.node_manager import NodeManager

    base = NodeManager.POOL_ID_STRIDE
    master = JobMaster(
        num_nodes=2, min_nodes=1, auto_scale=True,
        heartbeat_timeout=0.5, pools={"coworker": 1},
    )
    try:
        nm = master.node_manager
        import time as _t
        nm.report_event(base, "started")
        nm.ensure_node(base).last_heartbeat = _t.time() - 10
        dead = nm.check_heartbeats()
        assert dead == [base]
        master._handle_node_death(base)
        # Relaunched (budget-limited), not abandoned.
        assert nm.statuses()[base] == "pending"
    finally:
        master.stop()


def test_migration_survives_failed_old_node_and_failed_launch():
    from dlrover_tpu.master.node_manager import NodeLauncher, NodeManager

    class FlakyLauncher(NodeLauncher):
        def __init__(self):
            self.fail_next_launch = False
            self.launched, self.deleted = [], []

        def launch(self, node_id):
            if self.fail_next_launch:
                self.fail_next_launch = False
                raise RuntimeError("quota")
            self.launched.append(node_id)

        def delete(self, node_id):
            self.deleted.append(node_id)

    launcher = FlakyLauncher()
    nm = NodeManager(num_nodes=1, launcher=launcher,
                     pools={"coworker": 1})
    base = NodeManager.POOL_ID_STRIDE
    nm.report_event(base, "started")

    # Replacement launch fails -> full rollback, original keeps serving.
    launcher.fail_next_launch = True
    assert nm.migrate(base) is None
    assert nm.statuses()[base] == "running"
    assert not nm._migrations

    # Successful migration; the draining original then reports failed:
    # no relaunch at the old id (its replacement is already in flight).
    new_id = nm.migrate(base)
    assert new_id is not None
    launched_before = list(launcher.launched)
    nm.report_event(base, "failed", "preempted")
    assert launcher.launched == launched_before  # no old-id relaunch
    nm.report_event(new_id, "started")
    assert base in launcher.deleted  # original retired on completion


def test_pool_classifiers_agree_out_of_range():
    from dlrover_tpu.master.node_manager import NodeManager

    nm = NodeManager(num_nodes=1, pools={"coworker": 2})
    weird = 2 * NodeManager.POOL_ID_STRIDE + 5  # outside every pool range
    assert nm.pool_of(weird) == "worker"
    assert nm.ensure_node(weird).node_type == "worker"


def test_silent_death_mid_migration_not_relaunched_and_job_completes():
    """Code-review r5 round 2: a draining node that goes SILENT (the
    normal preemption signature) must not be relaunched at its old id,
    and pool nodes / migration rollbacks must not pin all_succeeded."""
    from dlrover_tpu.master.node_manager import NodeLauncher, NodeManager

    class Recorder(NodeLauncher):
        def __init__(self):
            self.launched, self.deleted = [], []

        def launch(self, node_id):
            self.launched.append(node_id)

        def delete(self, node_id):
            self.deleted.append(node_id)

    launcher = Recorder()
    nm = NodeManager(num_nodes=1, launcher=launcher,
                     pools={"coworker": 1}, heartbeat_timeout=0.5)
    base = NodeManager.POOL_ID_STRIDE
    import time as _t
    nm.report_event(0, "started")
    nm.report_event(base, "started")
    new_id = nm.migrate(base)
    # The draining original goes silent; heartbeat death must NOT
    # relaunch it (replacement in flight).
    nm.ensure_node(base).last_heartbeat = _t.time() - 10
    assert base in nm.check_heartbeats()
    launched_before = list(launcher.launched)
    assert nm.launch_node(base)  # the death-handler repair path
    assert launcher.launched == launched_before
    nm.report_event(new_id, "started")

    # Worker succeeded -> job succeeded, coworkers notwithstanding.
    nm.report_event(0, "succeeded")
    assert nm.all_succeeded()


def test_migration_rollback_leaves_no_orphan():
    from dlrover_tpu.master.node_manager import NodeLauncher, NodeManager

    class Failing(NodeLauncher):
        def launch(self, node_id):
            raise RuntimeError("quota")

        def delete(self, node_id):
            pass

    nm = NodeManager(num_nodes=1, launcher=Failing(),
                     pools={"coworker": 1})
    base = NodeManager.POOL_ID_STRIDE
    nm.report_event(base, "started")
    assert nm.migrate(base) is None
    # No DEAD orphan replacement node left behind.
    assert sorted(nm.statuses(pool="coworker")) == [base]
    nm.report_event(0, "succeeded")
    assert nm.all_succeeded()


def _master_only_fakes(phases):
    """A fake (master, launcher) pair for _run_master_only: job_phase()
    yields from ``phases`` (a KeyboardInterrupt instance raises)."""
    calls = []
    seq = iter(phases)

    class FakeMaster:
        node_manager = type("NM", (), {"job_failure_reason": "boom"})()

        def start(self):
            return 4711

        def bootstrap_nodes(self):
            calls.append("bootstrap")

        def job_phase(self):
            item = next(seq)
            if isinstance(item, BaseException):
                raise item
            return item

        def teardown_nodes(self):
            calls.append("teardown")

        def stop(self):
            calls.append("stop")

    class FakeLauncher:
        def shutdown(self):
            calls.append("shutdown")

    return FakeMaster(), FakeLauncher(), calls


@pytest.mark.parametrize("phases,rc,torn_down", [
    (["succeeded"], 0, True),
    (["failed"], 1, True),
    ([KeyboardInterrupt()], 130, False),
    ([RuntimeError("master crashed")], None, False),
])
def test_master_only_tears_down_only_on_terminal_phase(
    monkeypatch, phases, rc, torn_down
):
    """Ctrl-C / a master crash mid-job must NOT delete the worker VMs —
    a restarted master reattaches via state_path.  Only terminal job
    phases (succeeded/failed) clean up billing VMs."""
    import types

    from dlrover_tpu import run as run_mod

    master, launcher, calls = _master_only_fakes(phases)
    monkeypatch.setattr(
        run_mod, "build_cluster_master", lambda args: (master, launcher)
    )
    args = types.SimpleNamespace(cloud=True)
    if rc is None:
        with pytest.raises(RuntimeError, match="master crashed"):
            run_mod._run_master_only(args)
    else:
        assert run_mod._run_master_only(args) == rc
    assert ("teardown" in calls) == torn_down
    # The master itself and the launcher session always shut down.
    assert "stop" in calls and "shutdown" in calls
    if torn_down:  # cleanup ordering: VMs before the master goes away
        assert calls.index("teardown") < calls.index("stop")
