"""ZeRO-1 sharded weight update: parity vs the replicated update, the
quantized reduce-scatter wire, retrace accounting, cache keys, phase
plans, and the cross-world restore of sharded optimizer state.

Parity tests use SGD (linear in the gradient — see test_grad_accum.py's
rationale): the sharded update computes the SAME math as the replicated
one, 1/dp at a time, so the only divergence left is layout-dependent
reassociation in the bf16 forward/backward (GSPMD schedules the two
programs differently).  Loss parity is ~1e-5 relative; parameter parity
~1e-5 absolute (bf16 backward noise x the 1e-2 learning rate).  The
initial parameters themselves are BITWISE equal: init compiles against
the replicated shardings precisely because the non-partitionable threefry
RNG would otherwise generate different values under zero1 layouts.
"""

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from dlrover_tpu.models.gpt2 import gpt2_config
from dlrover_tpu.models.transformer import TransformerLM
from dlrover_tpu.optimizers.zero1 import (
    data_axis_dim,
    zero1_partition_spec,
)
from dlrover_tpu.parallel import rules as lr
from dlrover_tpu.parallel.quantized_collectives import (
    RING_MIN_BYTES,
    quantized_reduce_scatter,
    select_reduce_algo,
)
from dlrover_tpu.runtime.mesh import (
    ParallelConfig,
    build_mesh,
    shard_map_compat,
)
from dlrover_tpu.trainer import train_lib

import trace_asserts

TINY = gpt2_config(
    "124m", num_layers=2, d_model=64, num_heads=4,
    vocab_size=256, max_seq_len=64,
)

LOSS_RTOL = 2e-5        # bf16 forward reassociation across layouts
PARAM_RTOL, PARAM_ATOL = 1e-4, 1e-5


def _make_batch(batch=32, seq=16, vocab=256, seed=0):
    rng = np.random.default_rng(seed)
    tokens = rng.integers(0, vocab, size=(batch, seq + 1), dtype=np.int32)
    return {"inputs": tokens[:, :-1], "targets": tokens[:, 1:]}


def _build(zero1=False, grad_accum=1, reduce_quant="none",
           batch=32, seq=16, parallel=None):
    mesh = build_mesh(parallel or ParallelConfig(data=4, fsdp=2))
    model = TransformerLM(TINY)
    opt = train_lib.make_optimizer("sgd", learning_rate=1e-2)
    return train_lib.build_sharded_train(
        model, opt, mesh, lr.DEFAULT_RULES,
        global_batch_size=batch, seq_len=seq,
        grad_accum=grad_accum, reduce_quant=reduce_quant, zero1=zero1,
    )


def _run_steps(train, n_steps=1, batch=32, seq=16):
    state = train.init(jax.random.PRNGKey(0))
    losses = []
    for seed in range(n_steps):
        b = train_lib.shard_batch(
            _make_batch(batch, seq, TINY.vocab_size, seed), train
        )
        state, metrics = train.step(state, b)
        losses.append(float(metrics["loss"]))
    return state, losses


def _flat_params(state):
    leaves = jax.tree.leaves(state.params)
    return np.concatenate(
        [np.asarray(l, np.float64).ravel() for l in leaves]
    )


def _opt_specs_with_data_axis(state):
    shardings = jax.tree.leaves(
        jax.tree.map(lambda x: x.sharding, state.opt_state)
    )
    return sum(
        1 for s in shardings if data_axis_dim(s.spec) is not None
    ), len(shardings)


# -- spec derivation (pure unit) ----------------------------------------------


def test_zero1_partition_spec_appends_data_axis():
    sizes = {"data": 4, "fsdp": 2}
    # First divisible dim takes the axis, composed with the existing axis.
    assert zero1_partition_spec((64, 64), P(None, "fsdp"), sizes) == \
        P("data", "fsdp")
    # Dim 0 not divisible by dp -> falls through to dim 1.
    assert zero1_partition_spec((6, 64), P(), sizes) == P(None, "data")
    # Composes INTO a dim already sharded by fsdp when dim % (2*4) == 0.
    assert zero1_partition_spec((64,), P("fsdp"), sizes) == \
        P(("fsdp", "data"))


def test_zero1_partition_spec_refuses_unshardable():
    sizes = {"data": 4, "fsdp": 2}
    assert zero1_partition_spec((), P(), sizes) is None          # scalar
    assert zero1_partition_spec((6, 7), P(), sizes) is None      # indivisible
    assert zero1_partition_spec(
        (64, 64), P("data", None), sizes
    ) is None                                                    # already dp
    assert zero1_partition_spec((64, 64), P(), {"data": 1}) is None  # dp=1


def test_data_axis_dim():
    assert data_axis_dim(P("data", None)) == 0
    assert data_axis_dim(P(None, ("fsdp", "data"))) == 1
    assert data_axis_dim(P("fsdp", None)) is None
    assert data_axis_dim(P()) is None


# -- topology-aware algorithm choice ------------------------------------------


def test_select_reduce_algo():
    big = 8 * RING_MIN_BYTES
    # DCN crossing: latency per hop ~100x ICI -> one-shot always.
    assert select_reduce_algo(8, big, crosses_dcn=True) == "oneshot"
    # Tiny groups: n-1 hops of a 2-ring are pure overhead.
    assert select_reduce_algo(2, big) == "oneshot"
    # Small payloads: latency-bound.
    assert select_reduce_algo(8, RING_MIN_BYTES // 2) == "oneshot"
    # Large ICI payloads: bandwidth-optimal ring.
    assert select_reduce_algo(8, big) == "ring"
    assert select_reduce_algo(4, big) == "ring"
    # Unknown payload (0) defaults to ring for big groups on ICI.
    assert select_reduce_algo(8) == "ring"


# -- the quantized reduce-scatter wire ----------------------------------------


@pytest.mark.parametrize("algo", ["oneshot", "ring"])
def test_quantized_reduce_scatter_matches_mean(algo):
    """Member i's output chunk matches chunk i of the exact mean, for both
    lowerings — the ring's per-hop requantization stays inside the block
    error bound at n=4."""
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual mesh")
    mesh = build_mesh(ParallelConfig(data=4, fsdp=2))
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.normal(size=(4, 512)), jnp.float32)

    @functools.partial(
        shard_map_compat, mesh=mesh,
        in_specs=P("data", None), out_specs=P("data", None),
    )
    def rs(block):
        out = quantized_reduce_scatter(
            block[0], "data", dim=0, mean=True, algo=algo
        )
        return out[None]

    got = np.asarray(rs(x)).reshape(-1)       # member i -> rows [128i,128i+128)
    want = np.asarray(jnp.mean(x, axis=0))
    np.testing.assert_allclose(got, want, atol=0.05, rtol=0.05)


def test_quantized_reduce_scatter_indivisible_raises():
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual mesh")
    mesh = build_mesh(ParallelConfig(data=4, fsdp=2))
    x = jnp.zeros((4, 511), jnp.float32)

    @functools.partial(
        shard_map_compat, mesh=mesh,
        in_specs=P("data", None), out_specs=P("data", None),
    )
    def rs(block):
        return quantized_reduce_scatter(block[0], "data", dim=0)[None]

    with pytest.raises(ValueError, match="must divide"):
        rs(x)


# -- parity vs the replicated update ------------------------------------------


@pytest.mark.parametrize(
    "data,fsdp",
    [
        # (2, 4) compiles a second full mesh shape, ~11s on 1 core;
        # (4, 2) stays as the tier-1 witness.
        pytest.param(2, 4, marks=pytest.mark.slow),
        (4, 2),
    ],
)
def test_zero1_parity(data, fsdp):
    """Sharded update == replicated update at dp in {2, 4}: same loss,
    same parameters after one SGD step, and the optimizer state actually
    carries the data axis."""
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual mesh")
    parallel = ParallelConfig(data=data, fsdp=fsdp)
    full_state, full_losses = _run_steps(_build(parallel=parallel))
    z_train = _build(zero1=True, parallel=parallel)
    assert z_train.zero1
    z_state, z_losses = _run_steps(z_train)
    np.testing.assert_allclose(z_losses, full_losses, rtol=LOSS_RTOL)
    np.testing.assert_allclose(
        _flat_params(z_state), _flat_params(full_state),
        rtol=PARAM_RTOL, atol=PARAM_ATOL,
    )
    sharded, total = _opt_specs_with_data_axis(z_state)
    assert sharded > 0, "no optimizer-state leaf took the data axis"
    stats = z_train.zero1_stats
    assert stats["dp"] == data
    assert stats["bytes_per_device_after"] < stats["bytes_per_device_before"]


@pytest.mark.slow  # 3-step trajectory doubles the parity compile, ~11s on 1 core
def test_zero1_loss_trajectory_parity():
    """Three steps on fresh batches: the trajectories stay within bf16
    layout-reassociation tolerance of each other (no compounding drift at
    this horizon)."""
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual mesh")
    _, full_losses = _run_steps(_build(), n_steps=3)
    _, z_losses = _run_steps(_build(zero1=True), n_steps=3)
    np.testing.assert_allclose(z_losses, full_losses, rtol=1e-4)


def test_zero1_grad_accum_parity():
    """zero1 composed with the microbatch engine: the deferred DP reduce
    becomes the reduce-scatter feeding the sharded update."""
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual mesh")
    full_state, full_losses = _run_steps(_build())
    z_state, z_losses = _run_steps(_build(zero1=True, grad_accum=4))
    np.testing.assert_allclose(z_losses[0], full_losses[0], rtol=1e-5)
    np.testing.assert_allclose(
        _flat_params(z_state), _flat_params(full_state),
        rtol=PARAM_RTOL, atol=PARAM_ATOL,
    )


@pytest.mark.parametrize(
    "grad_accum",
    [
        # grad_accum=1 is the degenerate scan; =4 exercises the same
        # transport plus accumulation.  Both are slow-marked (~19s each):
        # the int8 wire itself is graded in test_quantized_collectives and
        # the zero1 update by test_zero1_parity above.
        pytest.param(1, marks=pytest.mark.slow),
        pytest.param(4, marks=pytest.mark.slow),
    ],
)
def test_zero1_int8_reduce_parity(grad_accum):
    """zero1 + int8: the quantized payload rides the reduce-scatter leg
    only (params all-gather back in full precision), so the update stays
    within the single-quantization-round error bound of the fp32 path —
    with and without the microbatch engine in front."""
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual mesh")
    full_state, full_losses = _run_steps(_build())
    z_state, z_losses = _run_steps(
        _build(zero1=True, grad_accum=grad_accum, reduce_quant="int8")
    )
    np.testing.assert_allclose(z_losses[0], full_losses[0], rtol=1e-5)
    np.testing.assert_allclose(
        _flat_params(z_state), _flat_params(full_state),
        rtol=0.05, atol=1e-3,
    )


def test_zero1_one_retrace():
    """The sharded-update program compiles ONCE: repeated steps on fresh
    batches must not retrace."""
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual mesh")
    train = _build(zero1=True)
    state = train.init(jax.random.PRNGKey(0))

    def one_step(state, seed):
        b = train_lib.shard_batch(
            _make_batch(32, 16, TINY.vocab_size, seed), train
        )
        state, _ = train.step(state, b)
        return state

    state = one_step(state, 0)  # pays the single compilation
    with trace_asserts.assert_no_retrace("train_step"):
        for seed in (1, 2):
            state = one_step(state, seed)


def test_zero1_inactive_without_data_axis():
    """dp=1: zero1 degrades to the replicated update (no sharding to do),
    and the flag reports inactive so phase plans stay honest."""
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual mesh")
    train = _build(zero1=True, parallel=ParallelConfig(data=1, fsdp=8))
    assert not train.zero1
    assert train.zero1_stats is None


# -- bookkeeping: cache keys and phase plans ----------------------------------


def test_cache_key_includes_zero1():
    from dlrover_tpu.runtime.compile_cache import train_cache_key

    base = dict(global_batch_size=16, seq_len=16, optimizer="sgd")
    k1 = train_cache_key(TINY, (4, 2), **base)
    k2 = train_cache_key(TINY, (4, 2), **base, zero1=True)
    k3 = train_cache_key(TINY, (4, 2), **base, zero1=True, grad_accum=4)
    assert len({k1, k2, k3}) == 3


def test_zero1_phase_plan_covers_step():
    rows = train_lib.microbatch_phase_plan(4, "none", 1.0, zero1=True)
    accum = [r for r in rows if r["phase"] == "accumulate"]
    assert [r["micro"] for r in accum] == [0, 1, 2, 3]
    assert {r["phase"] for r in rows} == {
        "accumulate", "reduce_scatter", "shard_update", "allgather",
    }
    np.testing.assert_allclose(sum(r["dur"] for r in rows), 1.0, rtol=1e-6)
    # Rows tile the step contiguously (t0 of each == end of the previous).
    ordered = sorted(rows, key=lambda r: r["t0"])
    for prev, cur in zip(ordered, ordered[1:]):
        np.testing.assert_allclose(
            prev["t0"] + prev["dur"], cur["t0"], rtol=1e-6
        )
    # int8 prices the reduce-scatter leg cheaper; the all-gather leg
    # (full-precision params) is priced the same on both wires.
    q = train_lib.microbatch_phase_plan(4, "int8", 1.0, zero1=True)
    dur = lambda rs, p: next(r["dur"] for r in rs if r["phase"] == p)
    assert dur(q, "reduce_scatter") < dur(rows, "reduce_scatter")
    np.testing.assert_allclose(
        dur(q, "allgather"), dur(rows, "allgather"), rtol=1e-6
    )


def test_est_comm_time_rs_ag_split():
    """The comm model prices reduce-scatter + all-gather legs: full
    precision equals the classic all-reduce volume, int8 discounts ONLY
    the reduce-scatter leg (so it saves less than a full int8 all-reduce
    would — but more than half the fp wire)."""
    from dlrover_tpu.auto import est_comm_time

    cfg = TINY
    full = est_comm_time(cfg, ParallelConfig(data=8, fsdp=1), "none")
    q = est_comm_time(cfg, ParallelConfig(data=8, fsdp=1), "int8")
    assert full > 0
    assert q < full
    # int8 still pays the full-precision gather leg: at least half the
    # fp wire time remains.
    assert q > full / 2 * 0.9
    assert est_comm_time(cfg, ParallelConfig(data=1, fsdp=8), "int8") == 0.0


def test_pick_grad_accum_zero1_discounts_opt_state():
    """Sharding the optimizer state over dp can only help: the zero1 pick
    is never larger, and an adamw-sized opt state (8 B/param) on a tight
    HBM budget fits with a smaller N."""
    from dlrover_tpu.auto import pick_grad_accum

    parallel = ParallelConfig(data=8, fsdp=1)
    n = TINY.num_params()
    # Budget chosen so the replicated adamw opt state is the binding
    # constraint: fixed bytes ~ (4 + 8 B/param) replicated vs
    # (4 + 1 B/param) sharded.
    hbm = n * 4 + n * 8 / 8 + 6 * 2 ** 20
    base = pick_grad_accum(
        TINY, parallel, 64, 64, optimizer="adamw", hbm_bytes=hbm,
    )
    z = pick_grad_accum(
        TINY, parallel, 64, 64, optimizer="adamw", hbm_bytes=hbm,
        zero1=True,
    )
    assert z <= base
    assert z < base or base == 1


# -- cross-world restore of sharded optimizer state ---------------------------


@pytest.mark.slow  # cross-world restores also covered by test_resize's matrix
def test_zero1_opt_state_cross_world_restore(tmp_path, monkeypatch):
    """A train state whose opt_state carries the data axis round-trips
    through the PR 7 cross-world checkpoint path: saved by a 2-host world,
    restored into a 1-host world, every leaf value equal."""
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual mesh")
    from dlrover_tpu.checkpoint.engine import CheckpointEngine
    from dlrover_tpu.checkpoint.saver import AsyncCheckpointSaver

    monkeypatch.setenv(
        "DLROVER_TPU_JOB", f"z1{os.getpid()}_{tmp_path.name}"
    )
    monkeypatch.setenv("DLROVER_TPU_SOCKET_DIR", str(tmp_path / "socks"))

    train = _build(zero1=True, parallel=ParallelConfig(data=2, fsdp=4))
    state, _ = _run_steps(train)
    sharded, _ = _opt_specs_with_data_axis(state)
    assert sharded > 0
    # Host view of the device tree — what the engine serializes.
    tree = jax.tree.map(
        np.asarray, {"params": state.params, "opt_state": state.opt_state},
    )

    ckpt = str(tmp_path / "ckpt")
    n = 2
    savers, engines = [], []
    for h in range(n):
        saver = AsyncCheckpointSaver(ckpt, host_index=h, num_hosts=n)
        saver.set_world(list(range(n)))
        saver.start()
        savers.append(saver)
        engines.append(CheckpointEngine(
            ckpt, host_index=h, num_hosts=n, agree_step_fn=lambda c: c,
        ))
    try:
        for engine in engines:
            assert engine.save_to_storage(3, tree)
        assert engines[0].wait_saver(timeout=30)
    finally:
        for engine in engines:
            engine._shm.close(unlink=True)
        for saver in savers:
            saver.stop()

    restorer = CheckpointEngine(
        ckpt, host_index=0, num_hosts=1, agree_step_fn=lambda c: c,
    )
    try:
        step, loaded = restorer.load(
            treedef=jax.tree_util.tree_structure(tree)
        )
    finally:
        restorer._shm.close(unlink=True)
    assert step == 3
    got = jax.tree.leaves(loaded)
    want = jax.tree.leaves(tree)
    assert len(got) == len(want)
    for g, w in zip(got, want):
        np.testing.assert_array_equal(np.asarray(g), np.asarray(w))


# -- tier-1 smoke: the trainer path on the virtual mesh -----------------------


def test_elastic_trainer_zero1_smoke(tmp_path, monkeypatch):
    """The full trainer stack runs a dp>=2 sharded-update step on the
    virtual CPU mesh every tier-1 run — the path is exercised in CI, not
    only in bench rounds."""
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual mesh")
    from dlrover_tpu.trainer.elastic_trainer import (
        ElasticTrainer,
        TrainerConfig,
    )

    monkeypatch.setenv(
        "DLROVER_TPU_JOB", f"z1s{os.getpid()}_{tmp_path.name}"
    )
    monkeypatch.setenv("DLROVER_TPU_SOCKET_DIR", str(tmp_path / "socks"))

    def loader(n, batch=16, seq=16, seed=0):
        rng = np.random.default_rng(seed)
        for _ in range(n):
            t = rng.integers(0, 256, size=(batch, seq + 1), dtype=np.int32)
            yield {"inputs": t[:, :-1], "targets": t[:, 1:]}

    cfg = gpt2_config(
        "124m", num_layers=1, d_model=64, num_heads=2,
        vocab_size=256, max_seq_len=16,
    )
    trainer = ElasticTrainer(
        cfg,
        TrainerConfig(
            global_batch_size=16, seq_len=16, optimizer="sgd",
            learning_rate=1e-2, zero1=True,
        ),
        client=None,
        parallel=ParallelConfig(data=2, fsdp=4),
    )
    try:
        assert trainer.train.zero1
        assert trainer._accum_extra()["zero1"] is True
        metrics = None
        for batch in loader(2):
            metrics = trainer.train_step(batch)
        assert np.isfinite(float(metrics["loss"]))
        sharded, _ = _opt_specs_with_data_axis(trainer.state)
        assert sharded > 0
    finally:
        trainer.close()
