"""Flash attention kernel vs XLA reference, fwd + grads, masks, GQA."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlrover_tpu.models.attention import xla_attention
from dlrover_tpu.ops import flash_attention as fa


def _rand_qkv(rng, b, s, hq, hkv, d, dtype=jnp.float32):
    q = jnp.asarray(rng.normal(size=(b, s, hq, d)), dtype)
    k = jnp.asarray(rng.normal(size=(b, s, hkv, d)), dtype)
    v = jnp.asarray(rng.normal(size=(b, s, hkv, d)), dtype)
    return q, k, v


@pytest.mark.parametrize("causal", [True, False])
def test_fwd_matches_xla(rng, causal):
    q, k, v = _rand_qkv(rng, 2, 256, 4, 4, 64)
    out = fa.mha(q, k, v, causal=causal, block_q=128, block_kv=128)
    ref = xla_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_fwd_gqa(rng):
    q, k, v = _rand_qkv(rng, 1, 256, 8, 2, 64)
    out = fa.mha(q, k, v, causal=True, block_q=128, block_kv=128)
    ref = xla_attention(q, k, v, causal=True)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_fwd_segment_mask(rng):
    b, s = 2, 256
    q, k, v = _rand_qkv(rng, b, s, 2, 2, 64)
    seg = jnp.asarray(
        rng.integers(0, 3, size=(b, s)).cumsum(axis=1) // 40, jnp.int32
    )
    out = fa.mha(
        q, k, v, causal=True, segment_ids=seg, block_q=128, block_kv=128
    )
    ref = xla_attention(q, k, v, causal=True, segment_ids=seg)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_fwd_unpadded_seq(rng):
    """Sequence not a multiple of the block: wrapper pads + masks."""
    q, k, v = _rand_qkv(rng, 1, 200, 2, 2, 64)
    out = fa.mha(q, k, v, causal=True, block_q=128, block_kv=128)
    ref = xla_attention(q, k, v, causal=True)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("hq,hkv", [(4, 4), (4, 2)])
def test_grads_match_xla(rng, hq, hkv):
    q, k, v = _rand_qkv(rng, 1, 256, hq, hkv, 64)

    def loss_flash(q, k, v):
        return jnp.sum(
            fa.mha(q, k, v, causal=True, block_q=128, block_kv=128) ** 2
        )

    def loss_ref(q, k, v):
        return jnp.sum(xla_attention(q, k, v, causal=True) ** 2)

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gr, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(
            gf, gr, atol=5e-4, rtol=5e-4, err_msg=f"d{name}"
        )


def test_grads_with_segments(rng):
    b, s = 1, 256
    q, k, v = _rand_qkv(rng, b, s, 2, 2, 64)
    seg = jnp.asarray((np.arange(s) // 64)[None, :].repeat(b, 0), jnp.int32)

    def loss_flash(q):
        return jnp.sum(
            fa.mha(q, k, v, causal=True, segment_ids=seg,
                   block_q=128, block_kv=128)
        )

    def loss_ref(q):
        return jnp.sum(xla_attention(q, k, v, causal=True, segment_ids=seg))

    np.testing.assert_allclose(
        jax.grad(loss_flash)(q), jax.grad(loss_ref)(q), atol=5e-4, rtol=5e-4
    )


@pytest.mark.parametrize("hq,hkv,causal", [(4, 4, True), (4, 2, False)])
def test_grads_match_xla_fused_single_kv_block(rng, hq, hkv, causal):
    """block_kv == (padded) seq routes through the fused one-pass backward
    kernel — the default-config path on the bench shapes."""
    q, k, v = _rand_qkv(rng, 1, 256, hq, hkv, 64)

    def loss_flash(q, k, v):
        return jnp.sum(
            fa.mha(q, k, v, causal=causal, block_q=256, block_kv=256) ** 2
        )

    def loss_ref(q, k, v):
        return jnp.sum(xla_attention(q, k, v, causal=causal) ** 2)

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gr, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(
            gf, gr, atol=5e-4, rtol=5e-4, err_msg=f"d{name} (fused path)"
        )


def test_grads_fused_with_segments(rng):
    b, s = 1, 256
    q, k, v = _rand_qkv(rng, b, s, 2, 2, 64)
    seg = jnp.asarray((np.arange(s) // 64)[None, :].repeat(b, 0), jnp.int32)

    def loss_flash(q):
        return jnp.sum(
            fa.mha(q, k, v, causal=True, segment_ids=seg,
                   block_q=256, block_kv=256)
        )

    def loss_ref(q):
        return jnp.sum(xla_attention(q, k, v, causal=True, segment_ids=seg))

    np.testing.assert_allclose(
        jax.grad(loss_flash)(q), jax.grad(loss_ref)(q), atol=5e-4, rtol=5e-4
    )
