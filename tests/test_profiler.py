"""Profiler subsystem: trace parsing, module attribution, capture smoke."""

import gzip
import json

import jax
import jax.numpy as jnp
import numpy as np

from dlrover_tpu.utils import profiler


def _write_trace(path, events):
    with gzip.open(path, "wt") as f:
        json.dump({"traceEvents": events}, f)


def test_parse_chrome_trace_aggregates_device_ops(tmp_path):
    path = str(tmp_path / "x.trace.json.gz")
    meta = [
        {"ph": "M", "name": "process_name", "pid": 3,
         "args": {"name": "/device:TPU:0"}},
        {"ph": "M", "name": "process_name", "pid": 9,
         "args": {"name": "/host:CPU"}},
    ]
    dev = [
        {"ph": "X", "pid": 3, "name": "fusion.1", "dur": 1000,
         "args": {"long_name": '%fusion.1 = f32[] fusion(), metadata='
                               '{op_name="jit(f)/jvp(M)/while/body/blocks/'
                               'attn/qkv/dot_general"}'}},
        {"ph": "X", "pid": 3, "name": "fusion.1", "dur": 1000, "args": {}},
        {"ph": "X", "pid": 3, "name": "jit_train", "dur": 9999},  # envelope
        {"ph": "X", "pid": 3, "name": "while.13", "dur": 8888},   # envelope
        {"ph": "X", "pid": 9, "name": "host_thing", "dur": 7777}, # host lane
    ]
    prof = profiler.parse_chrome_trace(path=_w(path, meta + dev), steps=2,
                                       wall_s=0.5)
    assert prof.device_total_s == 2000 / 1e6
    assert len(prof.ops) == 1
    op = prof.ops[0]
    assert op.count == 2
    assert op.module == "blocks/attn/qkv"
    table = prof.by_module()
    assert table == {"blocks/attn/qkv": 2000 / 1e6}
    assert "fusion.1" in prof.table()


def _w(path, events):
    _write_trace(path, events)
    return path


def test_module_classification_fallback():
    op = profiler.OpProfile("bitcast_dynamic-update-slice_fusion.15",
                            1.0, 1, "")
    assert op.module == "grad-accumulate"
    assert profiler.OpProfile("all-reduce.7", 1.0, 1, "").module == "collective"


def test_mfu_computation():
    prof = profiler.StepProfile(steps=2, wall_s=1.0, device_total_s=1.0,
                                ops=[])
    assert np.isclose(prof.mfu(flops_per_step=1e12, peak_flops=4e12), 0.5)


def test_capture_smoke_cpu():
    """capture() must run end-to-end on the CPU backend (no device lanes in
    the trace is fine — it degrades to timing only)."""

    @jax.jit
    def step(x):
        return (x @ x).sum()

    x = jnp.ones((128, 128))
    prof = profiler.capture(step, (x,), steps=2)
    assert prof.steps == 2
    assert prof.wall_s > 0
    assert prof.per_step() >= 0
