"""Group-sparse optimizer family (Adagrad, Ftrl, Lamb beside Adam) and the
INT64_MIN side-slot fix.

Parity targets: optax implementations where one exists (adagrad, lamb),
TF-semantics NumPy references otherwise (ftrl) — mirroring the reference's
op-level optimizer tests for ``KvVariableGroupSparseApply*``
(``tfplus/kv_variable/ops/training_ops.cc``).
"""

import numpy as np
import optax
import pytest

import jax.numpy as jnp

from dlrover_tpu.embedding import EmbeddingTable, KVStore
from dlrover_tpu.embedding.store import _load_native

DIM = 8


def stores():
    out = [KVStore(DIM, native=False)]
    if _load_native() is not None:
        out.append(KVStore(DIM, native=True))
    return out


def _seed_store(store, keys, values):
    store.insert(keys, values)


def test_adagrad_matches_optax():
    keys = np.array([3, 7], np.int64)
    w0 = np.random.default_rng(0).normal(size=(2, DIM)).astype(np.float32)
    grads = [
        np.random.default_rng(i + 1).normal(size=(2, DIM)).astype(np.float32)
        for i in range(4)
    ]
    # optax.adagrad: initial accumulator 0, eps inside the sqrt-denominator
    opt = optax.adagrad(0.1, initial_accumulator_value=0.0, eps=1e-10)
    params = jnp.asarray(w0)
    state = opt.init(params)
    for g in grads:
        upd, state = opt.update(jnp.asarray(g), state, params)
        params = optax.apply_updates(params, upd)

    for store in stores():
        _seed_store(store, keys, w0)
        for g in grads:
            store.apply_group_adagrad(keys, g, lr=0.1, eps=1e-10)
        got = store.peek(keys)
        np.testing.assert_allclose(got, np.asarray(params), rtol=2e-5,
                                   atol=2e-6)


def _ftrl_reference(w0, grads, lr, l1, l2, beta):
    """TF FtrlV2 semantics (learning_rate_power = -0.5), accumulator 0."""
    w = w0.copy()
    acc = np.zeros_like(w)
    linear = np.zeros_like(w)
    for g in grads:
        acc_new = acc + g * g
        sigma = (np.sqrt(acc_new) - np.sqrt(acc)) / lr
        linear += g - sigma * w
        acc = acc_new
        quad = (beta + np.sqrt(acc_new)) / lr + 2.0 * l2
        w = np.where(np.abs(linear) > l1,
                     (np.sign(linear) * l1 - linear) / quad, 0.0)
    return w.astype(np.float32)


@pytest.mark.parametrize("l1,l2,beta", [(0.0, 0.0, 0.0), (0.01, 0.1, 0.5)])
def test_ftrl_matches_tf_semantics(l1, l2, beta):
    keys = np.array([11, -4], np.int64)
    w0 = np.random.default_rng(2).normal(size=(2, DIM)).astype(np.float32)
    grads = [
        np.random.default_rng(i + 9).normal(size=(2, DIM)).astype(np.float32)
        for i in range(5)
    ]
    want = _ftrl_reference(w0, grads, lr=0.05, l1=l1, l2=l2, beta=beta)
    for store in stores():
        _seed_store(store, keys, w0)
        for g in grads:
            store.apply_group_ftrl(keys, g, lr=0.05, l1=l1, l2=l2, beta=beta)
        got = store.peek(keys)
        np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-6)


def test_lamb_matches_optax_per_row():
    # optax.lamb computes one trust ratio per parameter tensor; feeding it a
    # single row at a time makes its "layer" exactly our per-row group.
    keys = np.array([21], np.int64)
    w0 = np.random.default_rng(5).normal(size=(1, DIM)).astype(np.float32)
    grads = [
        np.random.default_rng(i + 40).normal(size=(1, DIM)).astype(np.float32)
        for i in range(4)
    ]
    opt = optax.lamb(0.1, b1=0.9, b2=0.999, eps=1e-6, weight_decay=0.01)
    params = jnp.asarray(w0[0])
    state = opt.init(params)
    for g in grads:
        upd, state = opt.update(jnp.asarray(g[0]), state, params)
        params = optax.apply_updates(params, upd)

    for store in stores():
        _seed_store(store, keys, w0)
        for t, g in enumerate(grads, start=1):
            store.apply_group_lamb(keys, g, lr=0.1, b1=0.9, b2=0.999,
                                   eps=1e-6, weight_decay=0.01, t=t)
        got = store.peek(keys)
        np.testing.assert_allclose(got[0], np.asarray(params), rtol=2e-4,
                                   atol=2e-5)


def test_native_python_parity_all_optimizers():
    if _load_native() is None:
        pytest.skip("no native build")
    keys = np.array([1, 2, 3], np.int64)
    w0 = np.random.default_rng(8).normal(size=(3, DIM)).astype(np.float32)
    g = np.random.default_rng(9).normal(size=(3, DIM)).astype(np.float32)
    for apply_name, kwargs in [
        ("apply_group_adam", dict(lr=0.1, t=1)),
        ("apply_group_adagrad", dict(lr=0.1)),
        ("apply_group_ftrl", dict(lr=0.1, l1=0.01, l2=0.1, beta=0.2)),
        ("apply_group_lamb", dict(lr=0.1, t=1)),
    ]:
        native = KVStore(DIM, native=True)
        python = KVStore(DIM, native=False)
        for s in (native, python):
            _seed_store(s, keys, w0)
            getattr(s, apply_name)(keys, g, **kwargs)
        np.testing.assert_allclose(
            native.peek(keys), python.peek(keys), rtol=2e-6, atol=2e-7,
            err_msg=apply_name,
        )


def test_table_optimizer_selection_trains():
    for optimizer in EmbeddingTable.OPTIMIZERS:
        table = EmbeddingTable("t", DIM, optimizer=optimizer,
                               learning_rate=0.1, native=False)
        keys = np.array([4, 4, 8], np.int64)
        rows, unique, inverse = table.lookup(keys)
        before = table.store.peek(unique)
        table.apply_gradients(unique, np.ones((unique.size, DIM), np.float32))
        after = table.store.peek(unique)
        assert not np.allclose(before, after), optimizer


def test_table_rejects_unknown_optimizer():
    with pytest.raises(ValueError):
        EmbeddingTable("t", DIM, optimizer="sgd")


def test_int64_min_key_round_trips():
    """INT64_MIN's bit pattern equals the empty-slot sentinel: it must live
    in the side slot and survive lookup/train/export/evict (round-3
    advisor finding)."""
    key_min = np.iinfo(np.int64).min
    for store in stores():
        keys = np.array([key_min, 5], np.int64)
        rows = store.lookup(keys, init_scale=0.1, seed=3, step=1)
        assert len(store) == 2
        again = store.lookup(np.array([key_min], np.int64), 0.1, 3, step=2)
        np.testing.assert_array_equal(again[0], rows[0])
        assert len(store) == 2  # no re-insert
        # trains
        store.apply_group_adam(
            np.array([key_min], np.int64),
            np.ones((1, DIM), np.float32), lr=0.1, t=1,
        )
        trained = store.peek(np.array([key_min], np.int64))
        assert not np.allclose(trained[0], rows[0])
        # exports (and the value round-trips through insert)
        ekeys, erows, em, ev, ecounts, esteps = store.export()
        assert key_min in ekeys.tolist()
        idx = ekeys.tolist().index(key_min)
        np.testing.assert_array_equal(erows[idx], trained[0])
        assert ecounts[idx] == 2
        # evict honors freshness for the side slot too
        assert store.evict(min_step=10, min_count=10) == 2
        assert len(store) == 0


def test_int64_min_key_survives_growth():
    if _load_native() is None:
        pytest.skip("no native build")
    store = KVStore(DIM, initial_capacity=64, native=True)
    key_min = np.iinfo(np.int64).min
    row0 = store.lookup(np.array([key_min], np.int64), 0.1, 1, 1)
    store.lookup(np.arange(5000, dtype=np.int64), 0.1, 1, 2)  # forces grow()
    after = store.peek(np.array([key_min], np.int64))
    np.testing.assert_array_equal(after[0], row0[0])
    assert len(store) == 5001
