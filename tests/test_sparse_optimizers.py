"""Group-sparse optimizer family (Adagrad, Ftrl, Lamb beside Adam) and the
INT64_MIN side-slot fix.

Parity targets: optax implementations where one exists (adagrad, lamb),
TF-semantics NumPy references otherwise (ftrl) — mirroring the reference's
op-level optimizer tests for ``KvVariableGroupSparseApply*``
(``tfplus/kv_variable/ops/training_ops.cc``).
"""

import numpy as np
import optax
import pytest

import jax.numpy as jnp

from dlrover_tpu.embedding import EmbeddingTable, KVStore
from dlrover_tpu.embedding.store import _load_native

DIM = 8


def stores():
    out = [KVStore(DIM, native=False)]
    if _load_native() is not None:
        out.append(KVStore(DIM, native=True))
    return out


def _seed_store(store, keys, values):
    store.insert(keys, values)


def test_adagrad_matches_optax():
    keys = np.array([3, 7], np.int64)
    w0 = np.random.default_rng(0).normal(size=(2, DIM)).astype(np.float32)
    grads = [
        np.random.default_rng(i + 1).normal(size=(2, DIM)).astype(np.float32)
        for i in range(4)
    ]
    # optax.adagrad: initial accumulator 0, eps inside the sqrt-denominator
    opt = optax.adagrad(0.1, initial_accumulator_value=0.0, eps=1e-10)
    params = jnp.asarray(w0)
    state = opt.init(params)
    for g in grads:
        upd, state = opt.update(jnp.asarray(g), state, params)
        params = optax.apply_updates(params, upd)

    for store in stores():
        _seed_store(store, keys, w0)
        for g in grads:
            store.apply_group_adagrad(keys, g, lr=0.1, eps=1e-10)
        got = store.peek(keys)
        np.testing.assert_allclose(got, np.asarray(params), rtol=2e-5,
                                   atol=2e-6)


def _ftrl_reference(w0, grads, lr, l1, l2, beta):
    """TF FtrlV2 semantics (learning_rate_power = -0.5), accumulator 0."""
    w = w0.copy()
    acc = np.zeros_like(w)
    linear = np.zeros_like(w)
    for g in grads:
        acc_new = acc + g * g
        sigma = (np.sqrt(acc_new) - np.sqrt(acc)) / lr
        linear += g - sigma * w
        acc = acc_new
        quad = (beta + np.sqrt(acc_new)) / lr + 2.0 * l2
        w = np.where(np.abs(linear) > l1,
                     (np.sign(linear) * l1 - linear) / quad, 0.0)
    return w.astype(np.float32)


@pytest.mark.parametrize("l1,l2,beta", [(0.0, 0.0, 0.0), (0.01, 0.1, 0.5)])
def test_ftrl_matches_tf_semantics(l1, l2, beta):
    keys = np.array([11, -4], np.int64)
    w0 = np.random.default_rng(2).normal(size=(2, DIM)).astype(np.float32)
    grads = [
        np.random.default_rng(i + 9).normal(size=(2, DIM)).astype(np.float32)
        for i in range(5)
    ]
    want = _ftrl_reference(w0, grads, lr=0.05, l1=l1, l2=l2, beta=beta)
    for store in stores():
        _seed_store(store, keys, w0)
        for g in grads:
            store.apply_group_ftrl(keys, g, lr=0.05, l1=l1, l2=l2, beta=beta)
        got = store.peek(keys)
        np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-6)


def test_lamb_matches_optax_per_row():
    # optax.lamb computes one trust ratio per parameter tensor; feeding it a
    # single row at a time makes its "layer" exactly our per-row group.
    keys = np.array([21], np.int64)
    w0 = np.random.default_rng(5).normal(size=(1, DIM)).astype(np.float32)
    grads = [
        np.random.default_rng(i + 40).normal(size=(1, DIM)).astype(np.float32)
        for i in range(4)
    ]
    opt = optax.lamb(0.1, b1=0.9, b2=0.999, eps=1e-6, weight_decay=0.01)
    params = jnp.asarray(w0[0])
    state = opt.init(params)
    for g in grads:
        upd, state = opt.update(jnp.asarray(g[0]), state, params)
        params = optax.apply_updates(params, upd)

    for store in stores():
        _seed_store(store, keys, w0)
        for t, g in enumerate(grads, start=1):
            store.apply_group_lamb(keys, g, lr=0.1, b1=0.9, b2=0.999,
                                   eps=1e-6, weight_decay=0.01, t=t)
        got = store.peek(keys)
        np.testing.assert_allclose(got[0], np.asarray(params), rtol=2e-4,
                                   atol=2e-5)


def test_native_python_parity_all_optimizers():
    if _load_native() is None:
        pytest.skip("no native build")
    keys = np.array([1, 2, 3], np.int64)
    w0 = np.random.default_rng(8).normal(size=(3, DIM)).astype(np.float32)
    g = np.random.default_rng(9).normal(size=(3, DIM)).astype(np.float32)
    for apply_name, kwargs in [
        ("apply_group_adam", dict(lr=0.1, t=1)),
        ("apply_group_adagrad", dict(lr=0.1)),
        ("apply_group_ftrl", dict(lr=0.1, l1=0.01, l2=0.1, beta=0.2)),
        ("apply_group_lamb", dict(lr=0.1, t=1)),
    ]:
        native = KVStore(DIM, native=True)
        python = KVStore(DIM, native=False)
        for s in (native, python):
            _seed_store(s, keys, w0)
            getattr(s, apply_name)(keys, g, **kwargs)
        np.testing.assert_allclose(
            native.peek(keys), python.peek(keys), rtol=2e-6, atol=2e-7,
            err_msg=apply_name,
        )


def test_table_optimizer_selection_trains():
    for optimizer in EmbeddingTable.OPTIMIZERS:
        table = EmbeddingTable("t", DIM, optimizer=optimizer,
                               learning_rate=0.1, native=False)
        keys = np.array([4, 4, 8], np.int64)
        rows, unique, inverse = table.lookup(keys)
        before = table.store.peek(unique)
        grads = np.ones((unique.size, DIM), np.float32)
        extra = {}
        if optimizer == "adahessian":
            extra["hessian_rows"] = 0.5 * grads
        table.apply_gradients(unique, grads, **extra)
        after = table.store.peek(unique)
        assert not np.allclose(before, after), optimizer
    with pytest.raises(ValueError, match="hessian_rows"):
        t = EmbeddingTable("t2", DIM, optimizer="adahessian", native=False)
        _, unique, _ = t.lookup(np.array([1], np.int64))
        t.apply_gradients(unique, np.ones((1, DIM), np.float32))


def test_table_rejects_unknown_optimizer():
    with pytest.raises(ValueError):
        EmbeddingTable("t", DIM, optimizer="sgd")


def test_int64_min_key_round_trips():
    """INT64_MIN's bit pattern equals the empty-slot sentinel: it must live
    in the side slot and survive lookup/train/export/evict (round-3
    advisor finding)."""
    key_min = np.iinfo(np.int64).min
    for store in stores():
        keys = np.array([key_min, 5], np.int64)
        rows = store.lookup(keys, init_scale=0.1, seed=3, step=1)
        assert len(store) == 2
        again = store.lookup(np.array([key_min], np.int64), 0.1, 3, step=2)
        np.testing.assert_array_equal(again[0], rows[0])
        assert len(store) == 2  # no re-insert
        # trains
        store.apply_group_adam(
            np.array([key_min], np.int64),
            np.ones((1, DIM), np.float32), lr=0.1, t=1,
        )
        trained = store.peek(np.array([key_min], np.int64))
        assert not np.allclose(trained[0], rows[0])
        # exports (and the value round-trips through insert)
        ekeys, erows, em, ev, ecounts, esteps = store.export()
        assert key_min in ekeys.tolist()
        idx = ekeys.tolist().index(key_min)
        np.testing.assert_array_equal(erows[idx], trained[0])
        assert ecounts[idx] == 2
        # evict honors freshness for the side slot too
        assert store.evict(min_step=10, min_count=10) == 2
        assert len(store) == 0


def test_int64_min_key_survives_growth():
    if _load_native() is None:
        pytest.skip("no native build")
    store = KVStore(DIM, initial_capacity=64, native=True)
    key_min = np.iinfo(np.int64).min
    row0 = store.lookup(np.array([key_min], np.int64), 0.1, 1, 1)
    store.lookup(np.arange(5000, dtype=np.int64), 0.1, 1, 2)  # forces grow()
    after = store.peek(np.array([key_min], np.int64))
    np.testing.assert_array_equal(after[0], row0[0])
    assert len(store) == 5001


def _radam_reference(w0, grads, lr, b1, b2, eps, wd):
    """RAdam per the paper (Liu et al. 2020), rectifier defined for
    rho_t > 4; matches tfplus RectifiedAdam group-apply semantics."""
    w = w0.copy().astype(np.float64)
    m = np.zeros_like(w)
    v = np.zeros_like(w)
    rho_inf = 2.0 / (1.0 - b2) - 1.0
    for t, g in enumerate(grads, start=1):
        g = g.astype(np.float64)
        m = b1 * m + (1 - b1) * g
        v = b2 * v + (1 - b2) * g * g
        m_hat = m / (1 - b1 ** t)
        rho_t = rho_inf - 2.0 * t * (b2 ** t) / (1 - b2 ** t)
        if rho_t > 4.0:
            rect = np.sqrt(((rho_t - 4) * (rho_t - 2) * rho_inf)
                           / ((rho_inf - 4) * (rho_inf - 2) * rho_t))
            update = rect * m_hat / (np.sqrt(v / (1 - b2 ** t)) + eps)
        else:
            update = m_hat
        w = w - lr * (update + wd * w)
    return w.astype(np.float32)


def test_radam_matches_paper_reference():
    keys = np.array([5, 9], np.int64)
    w0 = np.random.default_rng(4).normal(size=(2, DIM)).astype(np.float32)
    grads = [
        np.random.default_rng(i + 20).normal(size=(2, DIM)).astype(
            np.float32
        )
        for i in range(6)  # crosses the rho_t > 4 warmup boundary
    ]
    want = _radam_reference(w0, grads, lr=0.1, b1=0.9, b2=0.999,
                            eps=1e-8, wd=0.01)
    for store in stores():
        _seed_store(store, keys, w0)
        for t, g in enumerate(grads, start=1):
            store.apply_group_radam(keys, g, lr=0.1, b1=0.9, b2=0.999,
                                    eps=1e-8, weight_decay=0.01, t=t)
        np.testing.assert_allclose(store.peek(keys), want, rtol=2e-4,
                                   atol=2e-5)


def test_adahessian_scales_by_curvature_not_gradient():
    """v tracks h^2: with h = 2*g the steps must shrink vs adam-like
    h = g (the defining property of the curvature-scaled update)."""
    keys = np.array([1], np.int64)
    w0 = np.ones((1, DIM), np.float32)
    g = np.full((1, DIM), 0.5, np.float32)
    for store in stores():
        flat = KVStore(DIM, native=store.native)
        _seed_store(store, keys, w0)
        _seed_store(flat, keys, w0)
        store.apply_group_adahessian(keys, g, hessian=2 * g, lr=0.1, t=1)
        flat.apply_group_adahessian(keys, g, hessian=g, lr=0.1, t=1)
        step_big_h = np.abs(1.0 - store.peek(keys))
        step_small_h = np.abs(1.0 - flat.peek(keys))
        assert np.all(step_big_h < step_small_h)


def test_native_python_parity_radam_adahessian():
    if _load_native() is None:
        pytest.skip("no native build")
    keys = np.array([1, 2, 3], np.int64)
    w0 = np.random.default_rng(8).normal(size=(3, DIM)).astype(np.float32)
    g = np.random.default_rng(9).normal(size=(3, DIM)).astype(np.float32)
    h = np.abs(np.random.default_rng(10).normal(size=(3, DIM))).astype(
        np.float32
    )
    for apply_name, kwargs in [
        ("apply_group_radam", dict(lr=0.1, t=1, weight_decay=0.01)),
        ("apply_group_radam", dict(lr=0.1, t=50)),  # past the rectifier
        ("apply_group_adahessian", dict(hessian=h, lr=0.1, t=2)),
    ]:
        native = KVStore(DIM, native=True)
        python = KVStore(DIM, native=False)
        for s in (native, python):
            _seed_store(s, keys, w0)
            getattr(s, apply_name)(keys, g, **kwargs)
        # NumPy promotes the bias-corrected intermediates to float64; the
        # C row math stays float32 — small-t bias terms amplify the
        # rounding gap to ~1e-5.
        np.testing.assert_allclose(
            native.peek(keys), python.peek(keys), rtol=2e-5, atol=2e-5,
            err_msg=f"{apply_name} {kwargs}",
        )
