"""Flash Checkpoint <-> Orbax interop roundtrips."""

import jax
import jax.numpy as jnp
import numpy as np

from dlrover_tpu.checkpoint.orbax_interop import (
    export_to_orbax,
    flash_step_to_orbax,
    import_from_orbax,
)


def test_orbax_roundtrip_plain_tree(tmp_path):
    state = {
        "params": {"w": jnp.arange(12.0).reshape(3, 4)},
        "step": jnp.asarray(7),
    }
    path = export_to_orbax(str(tmp_path / "ckpt"), state)
    restored = import_from_orbax(path)
    np.testing.assert_array_equal(
        restored["params"]["w"], np.arange(12.0).reshape(3, 4)
    )
    assert int(restored["step"]) == 7


def test_flash_step_exports_to_orbax(tmp_path):
    from dlrover_tpu.checkpoint.engine import CheckpointEngine
    from dlrover_tpu.checkpoint.saver import AsyncCheckpointSaver

    ckpt_dir = str(tmp_path / "flash")
    saver = AsyncCheckpointSaver(ckpt_dir, host_index=0, num_hosts=1)
    saver.set_world([0])
    engine = CheckpointEngine(
        ckpt_dir, host_index=0, num_hosts=1, agree_step_fn=lambda c: c
    )
    state = {"w": jnp.full((4,), 2.5), "b": jnp.zeros((2,))}
    engine.save_to_memory(11, state)
    assert saver.save_step_checkpoint(11)

    step, path = flash_step_to_orbax(
        engine,
        str(tmp_path / "orbax"),
        treedef=jax.tree_util.tree_structure(state),
    )
    assert step == 11
    restored = import_from_orbax(path)
    np.testing.assert_allclose(restored["w"], np.full((4,), 2.5))
    engine._shm.close(unlink=True)
    engine.close()
    saver.stop()
