"""Serving survivability: replica fleet, RPC front door, live hot-swap.

Tier-1 coverage for the serving survivability layer:

1. the fleet — least-loaded routing, per-replica death via the
   ``replica.death`` seam with zero-lost in-flight resubmission, the
   total-loss orphan path, drain-before-retire and the
   ``ServeScalePolicy`` hooks;
2. the front door — submit/poll/cancel lifecycle over typed messages,
   bounded admission (``queue_full``), predicted-wait load shedding
   (fast reject, ``shed``), ``no_fleet``, and the ``serve.rpc`` seam;
3. live weight hot-swap — record-mapped reshard from a committed
   checkpoint between decode steps with zero retrace and no slot drain,
   digest verification bitwise against the ``state_digest`` fold, and
   rollback when the ``serve.swap`` seam corrupts the landed tree.
"""

import os
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlrover_tpu.common import faults
from dlrover_tpu.master import messages as msg
from dlrover_tpu.master.auto_scaler import ServeScalePolicy
from dlrover_tpu.models.transformer import TransformerConfig, TransformerLM
from dlrover_tpu.rl.generation import SamplingParams
from dlrover_tpu.serving import (
    NoReplicaError,
    ReplicaFleet,
    Request,
    ServeFrontend,
    ServingEngine,
)
from dlrover_tpu.serving import hotswap
from dlrover_tpu.trainer import train_lib

VOCAB, SEQ = 64, 32


@pytest.fixture(autouse=True)
def _isolated(monkeypatch, tmp_path):
    """Unique shm/job tag + socket dir per test; no fault plan leaks."""
    monkeypatch.setenv("DLROVER_TPU_JOB", f"sf{os.getpid()}_{tmp_path.name}")
    monkeypatch.setenv("DLROVER_TPU_SOCKET_DIR", str(tmp_path / "socks"))
    faults.reset()
    yield
    faults.reset()


@pytest.fixture(scope="module")
def setup():
    config = TransformerConfig(
        vocab_size=VOCAB, d_model=32, num_heads=4, num_layers=2,
        d_ff=64, max_seq_len=SEQ,
    )
    params = TransformerLM(config).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32)
    )["params"]
    return config, params


def _engine(setup, slots=2, seed=0):
    config, params = setup
    return ServingEngine(config, params, slots=slots, seed=seed)


def _req(uid, n=5, new=4):
    prompt = (np.arange(n, dtype=np.int32) % (VOCAB - 1)) + 1
    return Request(
        uid=uid, prompt=prompt, sampling=SamplingParams(max_new_tokens=new)
    )


def _run(fleet, budget=400):
    for _ in range(budget):
        if fleet.pending() == 0:
            return True
        fleet.step()
    return fleet.pending() == 0


# -- fleet: routing -----------------------------------------------------------


def test_least_loaded_routing_spreads_submissions(setup):
    fleet = ReplicaFleet()
    fleet.add_replica(_engine(setup))
    fleet.add_replica(_engine(setup, seed=1))
    assigned = [fleet.submit(_req(f"r{i}")) for i in range(4)]
    assert assigned == [
        "replica-0", "replica-1", "replica-0", "replica-1",
    ]
    assert _run(fleet)
    assert sorted(fleet.results) == ["r0", "r1", "r2", "r3"]


def test_unroutable_replicas_are_skipped(setup):
    fleet = ReplicaFleet()
    fleet.add_replica(_engine(setup))
    fleet.add_replica(_engine(setup, seed=1))
    fleet._replicas["replica-0"].draining = True
    assert fleet.submit(_req("a")) == "replica-1"
    fleet._replicas["replica-1"].breaker.record_failure()
    fleet._replicas["replica-1"].breaker.record_failure()
    fleet._replicas["replica-1"].breaker.record_failure()
    with pytest.raises(NoReplicaError):
        fleet.submit(_req("b"))


# -- fleet: death + failover --------------------------------------------------


def test_replica_death_resubmits_in_flight_zero_lost(setup):
    """The tentpole invariant: a replica dying mid-decode loses NOTHING —
    every unfinished request it held (queued and mid-flight) re-dispatches
    by id onto survivors and completes."""
    fleet = ReplicaFleet()
    fleet.add_replica(_engine(setup))
    fleet.add_replica(_engine(setup, seed=1))
    uids = [f"r{i}" for i in range(6)]
    for uid in uids:
        fleet.submit(_req(uid, new=6))
    # Fires walk the registry in order each step: hit 4 = step 2,
    # replica-1 — it dies holding live slots AND queued requests.
    faults.configure("replica.death:error@4", seed=0)
    assert _run(fleet)
    assert fleet.deaths == 1
    assert fleet.resubmitted >= 1
    assert fleet.replica_ids() == ["replica-0"]
    assert sorted(fleet.results) == uids  # zero lost
    assert all(len(fleet.results[u].tokens) > 0 for u in uids)


def test_last_replica_death_orphans_then_recovers(setup):
    fleet = ReplicaFleet()
    fleet.add_replica(_engine(setup))
    fleet.submit(_req("a"))
    fleet.submit(_req("b"))
    faults.configure("replica.death:error@1", seed=0)
    fleet.step()  # total loss: no survivors to resubmit onto
    faults.reset()
    assert fleet.replica_ids() == [] and fleet.deaths == 1
    assert fleet.pending() == 2 and not fleet.results
    # A fresh replica picks the orphans back up — still zero lost.
    fleet.add_replica(_engine(setup, seed=2))
    assert fleet.resubmit_orphans() == 2
    assert _run(fleet)
    assert sorted(fleet.results) == ["a", "b"]


def test_drain_retires_without_loss_and_respects_min_replicas(setup):
    fleet = ReplicaFleet()
    fleet.add_replica(_engine(setup))
    fleet.add_replica(_engine(setup, seed=1))
    for i in range(6):
        fleet.submit(_req(f"r{i}", new=5))
    fleet.step()
    fleet.drain("replica-0")
    assert fleet.retired == 1
    assert fleet.replica_ids() == ["replica-1"]
    assert _run(fleet)
    assert len(fleet.results) == 6  # the drained replica's work survived
    with pytest.raises(NoReplicaError):
        fleet.drain("replica-1")  # fleet at min_replicas


def test_maybe_scale_out_and_in(setup):
    fleet = ReplicaFleet(spawn=lambda: _engine(setup, seed=9))
    fleet.add_replica(_engine(setup))
    policy = ServeScalePolicy(slo_p95_s=1.0, min_qps=0.0)
    hot = dict(replicas=1.0, qps=5.0, p95_s=2.0, occupancy=0.9)
    fleet.stats = lambda: hot  # type: ignore[method-assign]
    assert fleet.maybe_scale(policy) == "out"
    assert len(fleet._replicas) == 2
    idle = dict(replicas=2.0, qps=5.0, p95_s=0.1, occupancy=0.05)
    fleet.stats = lambda: idle  # type: ignore[method-assign]
    assert fleet.maybe_scale(policy) == "in"
    assert len(fleet._replicas) == 1 and fleet.retired == 1


def test_scale_in_evicts_retired_replica_observability(setup):
    """Satellite regression: replicas leaving the fleet (drain/scale-in
    AND death) must drop their timeline + serve-ledger series — the same
    contract node retirement has (mirrors
    test_scale_down_evicts_observability_series)."""
    from dlrover_tpu.master.job_master import JobMaster

    master = JobMaster(num_nodes=2, auto_scale=False)
    fleet = ReplicaFleet()
    fleet.add_replica(_engine(setup))
    fleet.add_replica(_engine(setup, seed=1))
    master.attach_serve_frontend(ServeFrontend(fleet))
    assert fleet.retire_hook is not None
    for node in (0, 1):
        master.speed_monitor.record_serve(node, qps=2.0, requests=4.0)
        master.timeline.record(node, "step", kind="span", duration_s=0.1,
                               attrs={"step": 3})
    assert master.speed_monitor.serve_ledger()["replicas"] == 2.0
    # Scale-in path: drain retires replica-1 -> its series go.
    fleet.drain("replica-1")
    assert fleet.retired == 1
    assert master.speed_monitor.serve_ledger()["replicas"] == 1.0
    assert master.timeline.nodes() == [0]
    # Death path: kill exits the registry through the same hook.
    fleet.kill("replica-0", reason="test")
    assert master.speed_monitor.serve_ledger()["replicas"] == 0.0
    assert master.timeline.nodes() == []


def test_cancel_hits_only_queued_requests(setup):
    fleet = ReplicaFleet()
    fleet.add_replica(_engine(setup, slots=1))
    fleet.submit(_req("live"))
    fleet.submit(_req("queued"))
    fleet.step()  # "live" takes the only slot; "queued" waits
    assert fleet.cancel("queued") is True
    assert fleet.cancel("live") is False  # mid-decode: finishes its slot
    assert _run(fleet)
    assert "live" in fleet.results and "queued" not in fleet.results


# -- front door ---------------------------------------------------------------


def _submit_msg(uid, n=5, new=4, deadline_s=30.0):
    prompt = tuple(int(t) for t in ((np.arange(n) % (VOCAB - 1)) + 1))
    return msg.ServeSubmit(
        uid=uid, prompt=prompt, max_new_tokens=new, deadline_s=deadline_s
    )


def test_frontend_submit_poll_cancel_lifecycle(setup):
    fleet = ReplicaFleet()
    fleet.add_replica(_engine(setup))
    frontend = ServeFrontend(fleet)
    ticket = frontend.submit(_submit_msg("x", new=4))
    assert ticket.accepted
    assert frontend.poll(msg.ServePoll(uid="x")).state == "pending"
    assert _run(fleet)
    status = frontend.poll(msg.ServePoll(uid="x"))
    assert status.state == "done"
    assert len(status.tokens) == 4 and status.latency_s > 0
    # Cancel after completion is a no-op: the answer stands.
    assert frontend.cancel(msg.ServeCancel(uid="x")).state == "done"
    assert frontend.poll(msg.ServePoll(uid="nope")).state == "unknown"


def test_frontend_cancels_queued_request(setup):
    fleet = ReplicaFleet()
    fleet.add_replica(_engine(setup, slots=1))
    frontend = ServeFrontend(fleet)
    frontend.submit(_submit_msg("live"))
    frontend.submit(_submit_msg("queued"))
    fleet.step()
    assert frontend.cancel(msg.ServeCancel(uid="queued")).state == "cancelled"
    assert frontend.poll(msg.ServePoll(uid="queued")).state == "cancelled"


def test_frontend_bounded_queue_rejects_fast(setup):
    fleet = ReplicaFleet()
    fleet.add_replica(_engine(setup))
    frontend = ServeFrontend(fleet, max_pending=2)
    assert frontend.submit(_submit_msg("a")).accepted
    assert frontend.submit(_submit_msg("b")).accepted
    ticket = frontend.submit(_submit_msg("c"))
    assert not ticket.accepted and ticket.reason == "queue_full"
    assert frontend.poll(msg.ServePoll(uid="c")).state == "queue_full"
    assert frontend.rejected_full == 1


def test_frontend_sheds_when_predicted_wait_exceeds_deadline(setup):
    fleet = ReplicaFleet()
    fleet.add_replica(_engine(setup))
    frontend = ServeFrontend(fleet)
    # Cold fleet: no measured rate, no evidence to shed on — admit.
    assert frontend.submit(_submit_msg("warm0")).accepted
    assert frontend.submit(_submit_msg("warm1")).accepted
    assert _run(fleet)  # two completions: the engine has a measured qps
    assert fleet.service_rate() > 0
    for i in range(4):  # a backlog so predicted wait is non-zero
        frontend.submit(_submit_msg(f"bk{i}"))
    t0 = time.perf_counter()
    ticket = frontend.submit(_submit_msg("tight", deadline_s=1e-9))
    reject_s = time.perf_counter() - t0
    assert not ticket.accepted and ticket.reason == "shed"
    assert ticket.predicted_wait_s > 0
    assert reject_s < 0.1  # the whole point: an early cheap "no"
    assert frontend.poll(msg.ServePoll(uid="tight")).state == "shed"
    assert frontend.shed_count == 1
    assert _run(fleet)  # the accepted backlog still completes


def test_frontend_no_fleet_and_invalid_prompt(setup):
    frontend = ServeFrontend(ReplicaFleet())
    ticket = frontend.submit(_submit_msg("a"))
    assert not ticket.accepted and ticket.reason == "no_fleet"
    fleet = ReplicaFleet()
    fleet.add_replica(_engine(setup))
    frontend = ServeFrontend(fleet)
    bad = frontend.submit(_submit_msg("big", n=SEQ + 8))
    assert not bad.accepted and bad.reason.startswith("invalid")


def test_serve_rpc_seam_fails_one_rpc_then_recovers(setup):
    fleet = ReplicaFleet()
    fleet.add_replica(_engine(setup))
    frontend = ServeFrontend(fleet)
    faults.configure("serve.rpc:error@1", seed=0)
    with pytest.raises(faults.FaultInjected):
        frontend.submit(_submit_msg("a"))  # the caller's RetryPolicy re-issues
    assert frontend.submit(_submit_msg("a")).accepted  # hit 2: unscripted
    assert ("serve.rpc", "error", 1) in faults.active().fired


def test_front_door_over_the_real_servicer_wire(setup):
    """The tentpole transport claim: submit/poll/cancel ride the master's
    existing 2-RPC servicer — typed messages through the restricted
    unpickler, no new wire surface — and a reported ``serve.swap``
    telemetry event books into the master's swap ledger and gauges."""
    from dlrover_tpu.agent.master_client import MasterClient
    from dlrover_tpu.master.job_master import JobMaster

    fleet = ReplicaFleet()
    fleet.add_replica(_engine(setup))
    master = JobMaster(port=0, num_nodes=1)
    master.attach_serve_frontend(ServeFrontend(fleet))
    master.start()
    try:
        client = MasterClient(f"localhost:{master.port}", node_id=0)
        ticket = client.serve_submit(_submit_msg("wire", new=4))
        assert ticket.accepted
        assert client.serve_poll("wire").state == "pending"
        assert _run(fleet)
        status = client.serve_poll("wire")
        assert status.state == "done" and len(status.tokens) == 4
        assert client.serve_cancel("wire").state == "done"
        # An engine's serve.swap telemetry event lands in the ledger...
        client.report_telemetry([(
            "serve.swap", "point", time.time(), 0.25,
            {"ok": True, "rolled_back": False, "version": 2, "step": 5},
        )])
        ledger = master.speed_monitor.serve_ledger()
        assert ledger["swaps"] == 1.0 and ledger["weights_version"] == 2.0
        # ...and renders as gauges.
        metrics = client.get_metrics_text()
        assert "dlrover_serve_swaps_total 1" in metrics
        assert "dlrover_serve_weights_version 2" in metrics
        client.close()
    finally:
        master.stop()


def test_serve_rpc_without_frontend_is_a_clean_error(setup):
    from dlrover_tpu.agent.master_client import MasterClient
    from dlrover_tpu.master.job_master import JobMaster

    master = JobMaster(port=0, num_nodes=1)
    master.start()
    try:
        client = MasterClient(f"localhost:{master.port}", node_id=0)
        with pytest.raises(RuntimeError, match="no serving front door"):
            client.serve_submit(_submit_msg("x"))
        client.close()
    finally:
        master.stop()


# -- hot-swap -----------------------------------------------------------------


def _save_checkpoint(ckpt_dir, step, params):
    from dlrover_tpu.checkpoint.engine import CheckpointEngine
    from dlrover_tpu.checkpoint.saver import AsyncCheckpointSaver

    saver = AsyncCheckpointSaver(ckpt_dir, host_index=0, num_hosts=1)
    saver.set_world([0])
    saver.start()
    engine = CheckpointEngine(
        ckpt_dir, host_index=0, num_hosts=1, agree_step_fn=lambda c: c
    )
    assert engine.save_to_storage(step, {"params": params})
    assert engine.wait_saver(timeout=60)
    return engine, saver


def test_hotswap_mapping_and_host_digest_parity(setup):
    """Unit surfaces: the record mapper strips the checkpoint's
    ``['params']`` prefix and refuses drifted leaves; the host digest is
    bitwise the jitted ``state_digest`` fold."""
    from dlrover_tpu.trainer.state_digest import _digest_tree

    config, params = setup
    paths, leaves = hotswap.leaf_paths(params)
    arrays = {
        ("['params']",) + p: np.asarray(leaf)
        for p, leaf in zip(paths, leaves)
    }
    sources = hotswap.map_checkpoint_to_params(arrays, params)
    for src, leaf in zip(sources, leaves):
        np.testing.assert_array_equal(src, np.asarray(leaf))
    assert hotswap.host_digest(sources) == int(
        np.asarray(jax.jit(_digest_tree)(params))
    )
    missing = dict(arrays)
    missing.pop(next(iter(missing)))
    with pytest.raises(ValueError, match="no tensor"):
        hotswap.map_checkpoint_to_params(missing, params)
    drifted = {
        p: (a.reshape(-1, 1) if a.ndim == 2 else a)
        for p, a in arrays.items()
    }
    with pytest.raises(ValueError):
        hotswap.map_checkpoint_to_params(drifted, params)


def test_swap_weights_live_zero_retrace_then_rollback(setup, tmp_path):
    """The tentpole swap contract, both legs on one checkpoint: a clean
    swap lands between decode steps with zero retrace and no slot drain;
    a ``serve.swap``-corrupted swap is caught by the digest compare and
    rolls back to the serving tree."""
    config, params = setup
    swapped_params = jax.tree.map(lambda x: x * 1.5, params)
    ckpt_dir = str(tmp_path / "ckpt")
    ckpt_engine, saver = _save_checkpoint(ckpt_dir, 5, swapped_params)
    try:
        engine = ServingEngine(config, params, slots=2, seed=0)
        engine.submit(_req("a", new=6))
        engine.step()
        live_before = len(engine._live_slots())
        assert live_before == 1
        counts = {
            k: train_lib.TRACE_COUNTS[k]
            for k in ("serve_prefill", "serve_insert", "serve_decode")
        }
        report = engine.swap_weights(ckpt_dir)
        assert report["ok"] and not report["rolled_back"]
        assert report["step"] == 5 and report["version"] == 1
        assert engine.weights_version == 1
        np.testing.assert_array_equal(
            np.asarray(jax.tree.leaves(engine.params)[0]),
            np.asarray(jax.tree.leaves(swapped_params)[0]),
        )
        # No drain: the live slot kept its KV row through the swap...
        assert len(engine._live_slots()) == live_before
        engine.step()  # ...and keeps decoding under the new weights
        for name, before in counts.items():
            assert train_lib.TRACE_COUNTS[name] == before  # zero retrace

        # Corrupted leg: the seam flips a landed mantissa bit.
        faults.configure("serve.swap:error@1", seed=0)
        report2 = engine.swap_weights(ckpt_dir)
        faults.reset()
        assert not report2["ok"] and report2["rolled_back"]
        assert report2["version"] == 1  # version pinned to the good tree
        np.testing.assert_array_equal(
            np.asarray(jax.tree.leaves(engine.params)[0]),
            np.asarray(jax.tree.leaves(swapped_params)[0]),
        )
        results = engine.drain()
        assert "a" in results  # service never stopped
    finally:
        ckpt_engine._shm.close(unlink=True)
        saver.stop()


def test_swap_weights_without_committed_step_raises(setup, tmp_path):
    config, params = setup
    engine = ServingEngine(config, params, slots=2, seed=0)
    with pytest.raises(RuntimeError, match="no verifiable"):
        engine.swap_weights(str(tmp_path / "empty"))
