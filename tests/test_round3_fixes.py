"""Round-3 fix regressions: dead-node world invalidation, checkpoint
stale-world hygiene, restore lockstep, mixed-world-size step rejection."""

import os
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlrover_tpu.common.storage import CheckpointDirLayout, PosixDiskStorage
from dlrover_tpu.master.rdzv_manager import ElasticTrainingRendezvousManager


def _seal(manager, ranks):
    for r in ranks:
        manager.join_rendezvous(r, 1)
    manager.update_rdzv_params(
        min_nodes=len(ranks), max_nodes=len(ranks), waiting_timeout=0.1
    )
    round_, _, world = manager.get_comm_world(ranks[0])
    assert set(world) == set(ranks)
    return round_


def test_world_changed_on_member_death():
    m = ElasticTrainingRendezvousManager()
    round1 = _seal(m, [0, 1])
    assert not m.world_changed(round1)
    # A waiting stranger does not break the sealed world...
    m.join_rendezvous(7, 1)
    assert not m.world_changed(round1)
    del m._waiting_nodes[7]
    # ...but a member death does.
    m.remove_alive_node(1)
    assert m.world_changed(round1)
    # Survivor re-joins; the next sealed round clears the broken flag.
    m.update_rdzv_params(min_nodes=1, max_nodes=2, waiting_timeout=0.0)
    m.join_rendezvous(0, 1)
    import time

    time.sleep(0.05)
    round2, _, world = m.get_comm_world(0)
    assert world == {0: 1} and round2 == round1 + 1
    assert not m.world_changed(round2)
    # An older round is always "changed" once superseded.
    assert m.world_changed(round1)


def test_world_changed_ignores_non_member_death():
    m = ElasticTrainingRendezvousManager()
    round1 = _seal(m, [0, 1])
    m.remove_alive_node(5)  # never part of the world
    assert not m.world_changed(round1)


def test_master_control_loop_recovers_dead_node_shards():
    """Heartbeat death must evict the node from the rendezvous AND requeue
    its in-flight data shards (the round-2 verdict's dead-end path)."""
    from dlrover_tpu.master import messages as msg
    from dlrover_tpu.master.job_master import JobMaster

    master = JobMaster(num_nodes=2, min_nodes=1)
    master.node_manager.HEARTBEAT_TIMEOUT = 0.05
    try:
        rdzv = master.rdzv_managers["elastic-training"]
        round1 = _seal(rdzv, [0, 1])
        master.task_manager.create_dataset(
            msg.DatasetShardParams(
                dataset_name="d", dataset_size=100, shard_size=10
            )
        )
        task = master.task_manager.get_task("d", node_id=1)
        assert not task.empty
        master.node_manager.report_heartbeat(0, timestamp=__import__("time").time())
        master.node_manager.report_heartbeat(1, timestamp=0.0)  # stale
        newly_dead = master.node_manager.check_heartbeats()
        assert newly_dead == [1]
        master._handle_node_death(1)
        assert rdzv.world_changed(round1)
        # The dead node's shard is back in the queue for the survivor.
        recovered = master.task_manager.get_task("d", node_id=0)
        assert recovered.task_id == task.task_id
    finally:
        master.stop()


def test_saver_cleans_stale_world_files(tmp_path):
    """Re-saving a step after a world shrink must remove the old world's
    host files; restore then accepts the new world's complete group."""
    from dlrover_tpu.checkpoint.engine import CheckpointEngine
    from dlrover_tpu.checkpoint.saver import AsyncCheckpointSaver

    ckpt_dir = str(tmp_path / "ckpt")
    layout = CheckpointDirLayout(ckpt_dir)
    storage = PosixDiskStorage()
    # Old 2-host world persisted step 7 partially: host 1 died after its
    # persist, host 0 never finished -> files host_1_of_2.* + host_1.done.
    step_dir = layout.step_dir(7)
    storage.safe_makedirs(step_dir)
    storage.write(b"junk", layout.meta_path(7, 1, 2))
    storage.write(b"junk", layout.data_path(7, 1, 2))
    storage.write("ok:2", layout.done_path(7, 1))

    saver = AsyncCheckpointSaver(ckpt_dir, host_index=0)
    saver.set_world([0])
    engine = CheckpointEngine(
        ckpt_dir, host_index=0, num_hosts=1, agree_step_fn=lambda c: c
    )
    state = {"w": jnp.full((2,), 3.0)}
    engine.save_to_memory(7, state)
    assert saver.save_step_checkpoint(7)

    names = storage.listdir(step_dir)
    assert "host_1_of_2.meta" not in names
    assert "host_1_of_2.data" not in names
    assert "host_1.done" not in names
    assert layout.latest_step(storage) == 7
    engine._shm.close(unlink=True)
    step, loaded = engine.load_from_storage(
        treedef=jax.tree_util.tree_structure(state)
    )
    assert step == 7
    np.testing.assert_allclose(loaded["w"], [3.0, 3.0])
    engine.close()
    saver.stop()


def test_stale_done_files_cannot_satisfy_commit_barrier(tmp_path):
    """A done marker stamped by a different world size must not count."""
    from dlrover_tpu.checkpoint.saver import AsyncCheckpointSaver

    ckpt_dir = str(tmp_path / "ckpt")
    layout = CheckpointDirLayout(ckpt_dir)
    storage = PosixDiskStorage()
    storage.safe_makedirs(layout.step_dir(5))
    storage.write("ok:3", layout.done_path(5, 0))  # old 3-host world stamp

    saver = AsyncCheckpointSaver(
        ckpt_dir, host_index=0, num_hosts=1, commit_timeout=0.3
    )
    saver.commit_checkpoint(5, expected_hosts=[0], num_hosts=1)
    assert layout.latest_step(storage) == -1  # never committed
    storage.write(saver._done_stamp(1), layout.done_path(5, 0))
    saver.commit_checkpoint(5, expected_hosts=[0], num_hosts=1)
    assert layout.latest_step(storage) == 5
    saver.stop()


def test_restore_disambiguates_mixed_world_step(tmp_path):
    """Two self-consistent world-size groups in one step dir are no longer
    ambiguous: the done-marker commit barrier ranks the groups, so the
    committed world restores and a forged, uncommitted group is ignored
    (deterministically, not listdir-order luck)."""
    from dlrover_tpu.checkpoint.engine import CheckpointEngine
    from dlrover_tpu.checkpoint.saver import AsyncCheckpointSaver

    ckpt_dir = str(tmp_path / "ckpt")
    saver = AsyncCheckpointSaver(ckpt_dir, host_index=0, num_hosts=1)
    engine = CheckpointEngine(
        ckpt_dir, host_index=0, num_hosts=1, agree_step_fn=lambda c: c
    )
    old = {"w": jnp.full((2,), 1.0)}
    engine.save_to_memory(9, old)
    assert saver.save_step_checkpoint(9)
    layout = CheckpointDirLayout(ckpt_dir)
    storage = PosixDiskStorage()
    # Forge a second complete group (world size 2) in the same step dir.
    meta = storage.read(layout.meta_path(9, 0, 1))
    data = storage.read(layout.data_path(9, 0, 1))
    for host in (0, 1):
        storage.write(meta, layout.meta_path(9, host, 2))
        storage.write(data, layout.data_path(9, host, 2))
    engine._shm.close(unlink=True)
    step, loaded = engine.load_from_storage(
        treedef=jax.tree_util.tree_structure(old)
    )
    # The real world-1 group carries the only done marker (score 1/1 vs
    # 0/2), so it is the authority; the forged group never gets a vote.
    assert step == 9
    assert jnp.array_equal(loaded["w"], old["w"])
    engine.close()
    saver.stop()


def test_load_retry_stays_in_lockstep_across_hosts(tmp_path):
    """ADVICE medium: when the newest step is corrupt on ONE host only, both
    hosts must degrade to the older step together — the host whose local
    attempt succeeded keeps participating in the agreement collectives."""
    from dlrover_tpu.checkpoint.engine import CheckpointEngine
    from dlrover_tpu.checkpoint.saver import AsyncCheckpointSaver

    n = 2
    barrier = threading.Barrier(n)
    values = {}
    lock = threading.Lock()

    def make_agree(host):
        calls = {"i": 0}

        def agree(value):
            idx = calls["i"]
            calls["i"] += 1
            with lock:
                values.setdefault(idx, {})[host] = value
            barrier.wait(timeout=30)
            with lock:
                agreed = min(values[idx].values())
            barrier.wait(timeout=30)
            return agreed

        return agree

    dirs = [str(tmp_path / f"h{i}") for i in range(n)]
    savers = []
    state = {"w": jnp.full((2,), 1.0)}
    for host in range(n):
        # Separate checkpoint dirs model per-host storage visibility (the
        # corruption is host-local); same steps exist in both.
        saver = AsyncCheckpointSaver(dirs[host], host_index=host, num_hosts=1)
        saver.set_world([host])
        writer = CheckpointEngine(
            dirs[host], host_index=host, num_hosts=1,
            agree_step_fn=lambda c: c,
        )
        for step_num, val in ((10, 1.0), (20, 2.0)):
            writer.save_to_memory(step_num, {"w": jnp.full((2,), val)})
            assert saver.save_step_checkpoint(step_num)
        writer._shm.close(unlink=True)
        savers.append(saver)

    # Corrupt host 1's copy of step 20 only.
    os.remove(CheckpointDirLayout(dirs[1]).data_path(20, 1, 1))

    # Fresh engines (empty shm arenas): restore comes from storage.
    engines = [
        CheckpointEngine(
            dirs[host], host_index=host, num_hosts=n,
            agree_min_fn=make_agree(host),
        )
        for host in range(n)
    ]
    results = {}

    def load(host):
        results[host] = engines[host].load(
            treedef=jax.tree_util.tree_structure(state)
        )

    threads = [threading.Thread(target=load, args=(h,)) for h in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
        assert not t.is_alive(), "restore deadlocked across hosts"
    for host in range(n):
        step, loaded = results[host]
        assert step == 10, f"host {host} restored {step}, not the agreed 10"
        np.testing.assert_allclose(loaded["w"], [1.0, 1.0])
    for engine, saver in zip(engines, savers):
        engine._shm.close(unlink=True)
        engine.close()
        saver.stop()


@pytest.mark.slow  # full q8-adam train-step build, ~8s on 1 core
def test_make_optimizer_q8_adam_trains():
    """Round-2 verdict: the tested q8 Adam must be reachable from
    make_optimizer and drive a full sharded train step."""
    from dlrover_tpu.models.gpt2 import gpt2_config
    from dlrover_tpu.models.transformer import TransformerLM
    from dlrover_tpu.ops.quantization import Q8AdamState
    from dlrover_tpu.parallel import rules as lr
    from dlrover_tpu.runtime.mesh import ParallelConfig, build_mesh
    from dlrover_tpu.trainer import train_lib

    cfg = gpt2_config(
        "124m", num_layers=1, d_model=64, num_heads=2,
        vocab_size=512, max_seq_len=32,
    )
    model = TransformerLM(cfg)
    mesh = build_mesh(ParallelConfig(data=-1))
    opt = train_lib.make_optimizer("q8_adam", learning_rate=1e-2)
    train = train_lib.build_sharded_train(
        model, opt, mesh, lr.DEFAULT_RULES,
        global_batch_size=8, seq_len=32,
    )
    state = train.init(jax.random.PRNGKey(0))
    assert any(
        isinstance(leaf, Q8AdamState)
        for leaf in jax.tree.leaves(
            state.opt_state,
            is_leaf=lambda x: isinstance(x, Q8AdamState),
        )
    ), "optimizer state is not the quantized Q8AdamState"
    rng = np.random.default_rng(0)
    toks = rng.integers(0, 512, size=(8, 33), dtype=np.int32)
    batch = train_lib.shard_batch(
        {"inputs": toks[:, :-1], "targets": toks[:, 1:]}, train
    )
    losses = []
    for _ in range(4):
        state, metrics = train.step(state, batch)
        losses.append(float(metrics["loss"]))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]
