"""Classified HBM accounting plane: registry, ledger, gauges, forensics.

Covers the memory-truth chain end to end on the virtual CPU mesh:
``utils/memory_profile`` pricing + classification, the ``memory``
telemetry event and its servicer routing into ``MemoryLedger`` +
calibration, the ``dlrover_hbm_*`` gauges and the exposition lint
(every rendered ``dlrover_*`` metric carries ``# HELP``/``# TYPE``),
the ``/memory`` + ``/healthz`` HTTP surface, the ``HBMPressureOperator``
latch, ledger lifecycle through retirement/quarantine/state-snapshot,
and the OOM postmortem table.
"""

import gc
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlrover_tpu.common import telemetry
from dlrover_tpu.master import messages as msg
from dlrover_tpu.master.calibration import CalibrationLedger
from dlrover_tpu.master.diagnosis import (
    ActionType,
    DiagnosisContext,
    HBMPressureOperator,
)
from dlrover_tpu.master.memory_ledger import MemoryLedger
from dlrover_tpu.master.metrics import MetricsCollector
from dlrover_tpu.master.servicer import MasterServicer
from dlrover_tpu.master.speed_monitor import SpeedMonitor
from dlrover_tpu.master.timeline import JobTimeline
from dlrover_tpu.utils import memory_profile as mp


@pytest.fixture(autouse=True)
def _clean_registry():
    mp.registry().clear()
    yield
    mp.registry().clear()


# -- pricing + classification (the registry) --------------------------------


def test_per_device_nbytes_prices_the_shard():
    """A data-sharded array must price 1/dp of the global bytes — the
    property the whole measured-vs-modeled plane rests on."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    mesh = Mesh(np.array(jax.devices()[:4]), ("data",))
    arr = jax.device_put(
        jnp.zeros((16, 8), jnp.float32),
        NamedSharding(mesh, PartitionSpec("data", None)),
    )
    assert mp.per_device_nbytes(arr) == arr.nbytes // 4
    replicated = jax.device_put(
        jnp.zeros((16, 8), jnp.float32),
        NamedSharding(mesh, PartitionSpec(None, None)),
    )
    assert mp.per_device_nbytes(replicated) == replicated.nbytes


def test_registry_classifies_and_prices_pools():
    x = jnp.ones((64, 32), jnp.float32)
    reg = mp.BufferRegistry()
    reg.register("params", "t.params", lambda: {"w": x})
    reg.register("mystery", "t.mystery", lambda: [x])  # unknown -> other
    pools = reg.pool_bytes()
    assert pools["params"] == x.nbytes
    assert pools["other"] == x.nbytes
    rows = reg.rows()
    assert rows[0]["pool"] in ("params", "other")
    assert all(r["nbytes"] == x.nbytes for r in rows)
    assert {r["dtype"] for r in rows} == {"float32"}


def test_registry_weakmethod_provider_dies_with_owner():
    """A bound-method provider must not keep its owner alive: a dropped
    prefetcher/engine/cache self-unregisters at the next snapshot."""

    class Owner:
        def __init__(self):
            self.buf = jnp.ones((8, 8), jnp.float32)

        def buffers(self):
            return [self.buf]

    reg = mp.BufferRegistry()
    owner = Owner()
    reg.register("prefetch", "owner.buf", owner.buffers)
    assert reg.pool_bytes()["prefetch"] == owner.buf.nbytes
    del owner
    gc.collect()
    assert reg.pool_bytes()["prefetch"] == 0
    assert len(reg) == 0  # the dead entry was pruned, not just skipped


def test_registry_provider_exception_prices_zero():
    reg = mp.BufferRegistry()
    reg.register("kv_pool", "broken", lambda: 1 / 0)
    assert reg.pool_bytes()["kv_pool"] == 0


# -- the memory event --------------------------------------------------------


def test_emit_memory_event_disabled_costs_one_attr_read():
    recorder = telemetry.recorder()
    was_enabled = recorder.enabled
    recorder.configure(enabled=False)
    try:
        before = len(recorder.peek())
        assert mp.emit_memory_event(step=1) is None
        after = [ev for ev in recorder.peek() if ev[0] == "memory"]
        assert len(recorder.peek()) == before and after == []
    finally:
        recorder.configure(enabled=was_enabled)


def test_emit_memory_event_flat_attrs_and_analysis():
    x = jnp.ones((32, 16), jnp.float32)
    mp.registry().register("params", "t.params", lambda: [x])

    @jax.jit
    def f(a):
        return (a @ a.T).sum()

    compiled = f.lower(x).compile()
    mp.record_compiled_analysis("ck1", compiled)

    recorder = telemetry.recorder()
    was_enabled = recorder.enabled
    recorder.configure(enabled=True)
    try:
        recorder.drain()
        attrs = mp.emit_memory_event(
            step=7, cache_key="ck1", modeled_b=float(x.nbytes)
        )
        events = [ev for ev in recorder.drain() if ev[0] == "memory"]
    finally:
        recorder.configure(enabled=was_enabled)
    assert len(events) == 1
    name, kind, _, _, wired = events[0]
    assert wired["step"] == 7
    assert wired["cache_key"] == "ck1"
    assert wired["pool_params_b"] == x.nbytes
    assert wired["measured_b"] > 0
    assert wired["modeled_b"] == x.nbytes
    assert wired["xla_temp_b"] >= 0  # AOT analysis attached by cache key
    # Flat attrs only: everything the wire carries must be scalar.
    assert all(
        isinstance(v, (int, float, str)) for v in attrs.values()
    )


def test_servicer_routes_memory_events_to_ledger_and_calibration():
    timeline = JobTimeline()
    ledger = MemoryLedger()
    calibration = CalibrationLedger()
    servicer = MasterServicer(
        timeline=timeline, memory_ledger=ledger, calibration=calibration
    )
    event = ("memory", "event", 1000.0, 0.0, {
        "step": 3, "cache_key": "ck", "bytes_in_use": 800.0,
        "peak_bytes": 900.0, "limit_bytes": 1000.0,
        "headroom_frac": 0.2, "measured_b": 800.0, "modeled_b": 640.0,
        "pool_params_b": 500.0, "pool_opt_state_b": 300.0,
        "source": "allocator",
    })
    servicer._report_telemetry(msg.Envelope(
        node_id=2, node_type="worker", job_name="t",
        payload=msg.TelemetryEvents(node_id=2, events=(event,), dropped=0),
    ))
    assert len(ledger) == 1
    booked = ledger.per_node()[2]
    assert booked["bytes_in_use"] == 800.0
    assert booked["cache_key"] == "ck"
    assert ledger.headroom_frac() == pytest.approx(0.2)
    assert calibration.ratios()["memory"] == pytest.approx(800.0 / 640.0)
    # Malformed attrs must not take the servicer down.
    bad = ("memory", "event", 1000.0, 0.0, {"bytes_in_use": "junk"})
    servicer._report_telemetry(msg.Envelope(
        node_id=2, node_type="worker", job_name="t",
        payload=msg.TelemetryEvents(node_id=2, events=(bad,), dropped=0),
    ))
    assert len(ledger) == 1


# -- ledger lifecycle --------------------------------------------------------


def _snapshot_attrs(headroom=0.5, in_use=500.0):
    return {
        "bytes_in_use": in_use, "peak_bytes": in_use,
        "limit_bytes": 1000.0, "headroom_frac": headroom,
        "pool_params_b": in_use,
    }


def test_memory_ledger_newest_wins_evict_and_aggregate():
    ledger = MemoryLedger()
    ledger.record(0, **_snapshot_attrs(headroom=0.5))
    ledger.record(0, **_snapshot_attrs(headroom=0.4, in_use=600.0))
    ledger.record(1, **_snapshot_attrs(headroom=0.1))
    agg = ledger.ledger()
    assert agg["nodes"] == 2
    assert agg["events"] == 3
    assert agg["bytes_in_use"] == 1100.0
    assert ledger.headroom_frac() == pytest.approx(0.1)  # tightest node
    ledger.evict(1)
    assert ledger.headroom_frac() == pytest.approx(0.4)
    ledger.evict(99)  # unknown node: no-op
    assert len(ledger) == 1


def test_memory_ledger_unknown_headroom_is_not_pressure():
    ledger = MemoryLedger()
    ledger.record(0, bytes_in_use=100.0, headroom_frac=-1.0)
    assert ledger.headroom_frac() == -1.0
    ledger.record(1, **_snapshot_attrs(headroom=0.3))
    assert ledger.headroom_frac() == pytest.approx(0.3)


def test_memory_ledger_survives_master_state_snapshot(tmp_path):
    """Retirement/quarantine evict, and the ledger rides the master state
    snapshot through a restart round-trip."""
    from dlrover_tpu.master.job_master import JobMaster

    path = str(tmp_path / "master_state.json")
    master = JobMaster(num_nodes=2, min_nodes=1, state_path=path)
    try:
        master.memory_ledger.record(0, **_snapshot_attrs())
        master.memory_ledger.record(1, **_snapshot_attrs(headroom=0.2))
        master._state_store.save(master)
    finally:
        master.stop()

    reborn = JobMaster(num_nodes=2, min_nodes=1, state_path=path)
    try:
        reborn.start()
        assert len(reborn.memory_ledger) == 2
        assert reborn.memory_ledger.headroom_frac() == pytest.approx(0.2)

        # Quarantine evicts the node's stale snapshot with it.
        reborn.node_manager.ensure_node(1)
        reborn._quarantine_node(1, "digest minority x2")
        assert len(reborn.memory_ledger) == 1
        # Retirement evicts too.
        reborn.memory_ledger.record(5, **_snapshot_attrs())
        reborn._handle_node_retired(5)
        assert 5 not in reborn.memory_ledger.per_node()
    finally:
        reborn.stop()


# -- diagnosis ---------------------------------------------------------------


def _ctx(ledger):
    return DiagnosisContext(
        speed_monitor=None, metrics=None, node_manager=None, memory=ledger
    )


def test_hbm_pressure_operator_latches_and_rearms():
    ledger = MemoryLedger()
    op = HBMPressureOperator()
    assert op.observe(_ctx(None)) == []
    assert op.observe(_ctx(ledger)) == []  # empty ledger

    ledger.record(0, **_snapshot_attrs(headroom=0.5))
    ledger.record(1, **_snapshot_attrs(headroom=0.03))
    actions = op.observe(_ctx(ledger))
    assert len(actions) == 1
    assert actions[0].action == ActionType.REPORT
    assert actions[0].node_id == 1
    assert "headroom" in actions[0].reason
    assert op.observe(_ctx(ledger)) == []  # latched: one report per episode

    # Recovery past floor + hysteresis re-arms; a fresh breach re-fires.
    ledger.record(1, **_snapshot_attrs(headroom=0.4))
    assert op.observe(_ctx(ledger)) == []
    ledger.record(1, **_snapshot_attrs(headroom=0.02))
    assert len(op.observe(_ctx(ledger))) == 1


def test_hbm_pressure_operator_ignores_unknown_headroom():
    ledger = MemoryLedger()
    ledger.record(0, bytes_in_use=100.0, headroom_frac=-1.0)
    op = HBMPressureOperator()
    assert op.observe(_ctx(ledger)) == []


# -- gauges + exposition lint ------------------------------------------------


def _rendered_everything():
    timeline = JobTimeline()
    timeline.record(0, "step", kind="span", duration_s=0.1,
                    attrs={"step": 1})
    ledger = MemoryLedger()
    ledger.record(0, **_snapshot_attrs())
    calibration = CalibrationLedger()
    calibration.observe("ck", "memory", 800.0, 640.0)
    metrics = MetricsCollector()
    metrics.collect(0, 10.0, 1.0, 2.0, 0.5,
                    device_mem_max_gb=1.5, device_util_max=0.9)
    return timeline.render_metrics(
        speed_monitor=SpeedMonitor(), calibration=calibration,
        memory=ledger, metrics=metrics,
    )


def test_hbm_gauges_render_with_pool_labels():
    text = _rendered_everything()
    assert "dlrover_hbm_bytes_in_use 500" in text
    assert 'dlrover_hbm_pool_bytes{pool="params"} 500' in text
    assert 'dlrover_hbm_pool_bytes{pool="kv_pool"} 0' in text
    assert "dlrover_hbm_headroom_frac 0.5" in text
    assert 'dlrover_host_device_mem_max_gb{node="0"} 1.5' in text
    assert 'dlrover_host_device_util_max{node="0"} 0.9' in text
    assert 'dlrover_calibration_ratio{phase="memory"}' in text


def test_every_rendered_metric_has_help_and_type():
    """Exposition lint: every sample the master renders must carry both
    ``# HELP`` and ``# TYPE`` lines — half-documented gauges regress
    silently otherwise."""
    text = _rendered_everything()
    helped, typed, sampled = set(), set(), set()
    for line in text.splitlines():
        if line.startswith("# HELP "):
            helped.add(line.split()[2])
        elif line.startswith("# TYPE "):
            typed.add(line.split()[2])
        elif line.strip():
            name = line.split("{", 1)[0].split()[0]
            sampled.add(name)
    assert sampled, "lint ran against an empty exposition"
    assert sampled - helped == set(), "samples missing # HELP"
    assert sampled - typed == set(), "samples missing # TYPE"


# -- HTTP surface ------------------------------------------------------------


def _plane(hbm_floor=0.0, headroom=None):
    from dlrover_tpu.master.http_plane import MetricsHTTPServer

    ledger = MemoryLedger()
    if headroom is not None:
        ledger.record(0, **_snapshot_attrs(headroom=headroom))
    servicer = MasterServicer(
        timeline=JobTimeline(), memory_ledger=ledger
    )
    return MetricsHTTPServer(servicer, healthz_hbm_floor=hbm_floor)


def test_healthz_hbm_floor_default_off():
    plane = _plane(hbm_floor=0.0, headroom=0.01)
    health = plane.healthz()
    assert health["ok"] is True  # floor off: low headroom reported, not fatal
    assert health["hbm_headroom_frac"] == pytest.approx(0.01)


def test_healthz_flips_below_hbm_floor():
    assert _plane(hbm_floor=0.05, headroom=0.01).healthz()["ok"] is False
    assert _plane(hbm_floor=0.05, headroom=0.2).healthz()["ok"] is True
    # Unknown headroom (no allocator stats) never flips health.
    assert _plane(hbm_floor=0.05, headroom=None).healthz()["ok"] is True


def test_memory_endpoint_payload():
    plane = _plane(headroom=0.5)
    payload = json.loads(plane.memory_json())
    assert payload["ledger"]["nodes"] == 1
    assert payload["nodes"]["0"]["bytes_in_use"] == 500.0


# -- OOM forensics -----------------------------------------------------------


def test_is_oom_error_matches_resource_exhausted():
    assert mp.is_oom_error(RuntimeError("RESOURCE_EXHAUSTED: foo"))
    assert mp.is_oom_error(ValueError("Out of memory while allocating"))
    assert not mp.is_oom_error(ValueError("shape mismatch"))


def test_oom_postmortem_classifies_top_buffers(tmp_path):
    big = jnp.ones((256, 64), jnp.float32)
    small = jnp.ones((4, 4), jnp.float32)
    mp.registry().register("params", "t.params", lambda: [big])
    mp.registry().register("kv_pool", "t.kv", lambda: [small])
    path = mp.dump_oom_postmortem(
        str(tmp_path), error=RuntimeError("RESOURCE_EXHAUSTED: hbm"),
        cache_key="ck", top_n=5,
    )
    with open(path) as f:
        dump = json.load(f)
    assert "RESOURCE_EXHAUSTED" in dump["error"]
    assert dump["cache_key"] == "ck"
    assert dump["top"][0]["pool"] == "params"  # largest-first
    assert dump["top"][0]["nbytes"] == big.nbytes
    assert dump["pools_b"]["kv_pool"] == small.nbytes
    assert dump["rows_total"] == 2


def test_oom_postmortem_never_raises(tmp_path, monkeypatch):
    monkeypatch.setattr(mp._REGISTRY, "rows", lambda: 1 / 0)
    assert mp.dump_oom_postmortem(str(tmp_path), error=None) is None
