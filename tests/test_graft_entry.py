"""Validate the driver entry points (__graft_entry__.py) on the CPU mesh."""

import os
import sys

import jax
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import __graft_entry__ as graft  # noqa: E402


@pytest.mark.slow  # end-to-end driver dryrun over an 8-device virtual mesh
def test_dryrun_multichip_8():
    graft.dryrun_multichip(8)


@pytest.mark.slow  # end-to-end driver dryrun over an 8-device virtual mesh
def test_dryrun_multichip_2():
    graft.dryrun_multichip(2)


def test_entry_traces():
    """entry()'s fn must be jit-traceable (full compile check runs on TPU)."""
    fn, args = graft.entry()
    out = jax.eval_shape(fn, *args)
    assert out.shape == ()
