"""End-to-end elastic launch tests: CLI -> standalone master -> agent ->
trainer subprocess, with crash-restart-resume.

Mirrors the reference's chaos validation (SURVEY.md §4/§5: kill process,
observe relaunch & resumed step — ``fault_tolerance_exps.md``).
"""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_cli(tmp_path, child_env, extra_cli, extra_trainer, timeout=600):
    env = dict(child_env)
    env.update(
        {
            "DLROVER_TPU_SOCKET_DIR": str(tmp_path / "socks"),
            # Unique per test: the shm arena is named by job tag and outlives
            # processes, so two tests sharing a tag would see each other's
            # checkpoints.
            "DLROVER_TPU_JOB": f"e2e{os.getpid()}_{os.path.basename(tmp_path)}",
            # Append, never overwrite: the TPU relay plugin registers via a
            # sitecustomize dir already on PYTHONPATH.
            "PYTHONPATH": REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
        }
    )
    env.pop("XLA_FLAGS", None)
    cmd = (
        [sys.executable, "-m", "dlrover_tpu.run", "--standalone"]
        + extra_cli
        + ["--", sys.executable, os.path.join(REPO, "examples", "train_lm.py")]
        + extra_trainer
    )
    return subprocess.run(
        cmd, env=env, timeout=timeout, capture_output=True, text=True
    )


@pytest.mark.slow
def test_cli_standalone_training(tmp_path, cpu_child_env):
    ckpt_dir = str(tmp_path / "ckpt")
    result = _run_cli(
        tmp_path, cpu_child_env,
        ["--checkpoint-dir", ckpt_dir, "--monitor-interval", "1"],
        [
            "--steps", "8", "--ckpt-every", "4",
            "--checkpoint-dir", ckpt_dir,
            "--layers", "1", "--d-model", "64", "--heads", "2",
            "--seq-len", "64", "--batch-size", "4",
        ],
    )
    assert result.returncode == 0, result.stderr[-3000:]
    from dlrover_tpu.common.storage import CheckpointDirLayout, PosixDiskStorage

    assert CheckpointDirLayout(ckpt_dir).latest_step(PosixDiskStorage()) == 8


@pytest.mark.slow
def test_cli_crash_restart_resume(tmp_path, cpu_child_env):
    """Trainer crashes at step 6 (after the step-4 checkpoint); the agent
    restarts it in place; it resumes from step 4 and completes."""
    ckpt_dir = str(tmp_path / "ckpt")
    result = _run_cli(
        tmp_path, cpu_child_env,
        [
            "--checkpoint-dir", ckpt_dir, "--max-restarts", "2",
            "--monitor-interval", "1",
        ],
        [
            "--steps", "8", "--ckpt-every", "4",
            "--checkpoint-dir", ckpt_dir, "--fail-at-step", "6",
            "--layers", "1", "--d-model", "64", "--heads", "2",
            "--seq-len", "64", "--batch-size", "4",
        ],
    )
    assert result.returncode == 0, result.stderr[-3000:]
    combined = result.stdout + result.stderr
    assert "crashing at step 6" in combined
    assert "resumed from checkpoint at step 4" in combined
    from dlrover_tpu.common.storage import CheckpointDirLayout, PosixDiskStorage

    assert CheckpointDirLayout(ckpt_dir).latest_step(PosixDiskStorage()) == 8
