"""Overlap engine: bucket planning units + overlapped-vs-serialized
step parity under the no-retrace pin.

The structural claim the engine rests on — reduce-scatter is linear, so
per-microbatch scatter into a 1/dp-sharded accumulator equals one
scatter of the accumulated gradient — is asserted here as end-to-end
param parity between ``overlap=True`` and ``overlap=False`` builds of
the SAME mesh shape.  (Different mesh shapes legitimately diverge via
bf16 layout reassociation; parity is only meaningful holding the mesh
fixed.)
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import trace_asserts
from dlrover_tpu.models.gpt2 import gpt2_config
from dlrover_tpu.models.transformer import TransformerLM
from dlrover_tpu.parallel import overlap as overlap_lib
from dlrover_tpu.parallel import rules as lr
from dlrover_tpu.runtime.mesh import ParallelConfig, build_mesh
from dlrover_tpu.trainer import train_lib

TINY = gpt2_config(
    "124m", num_layers=2, d_model=64, num_heads=4,
    vocab_size=256, max_seq_len=64,
)

#: ZeRO-1 overlap parity: grad-accum reassociation + bf16 activation
#: noise over a few SGD steps (tests/test_zero1.py tolerances, atol
#: widened for the scan-interior scatter's extra reassociation).
PARITY_RTOL, PARITY_ATOL = 1e-4, 5e-5
#: int8 transports quantize once per microbatch leg.
INT8_RTOL, INT8_ATOL = 1e-2, 5e-3


# ---------------------------------------------------------------------------
# plan_buckets / scheduled_leaf_map units
# ---------------------------------------------------------------------------


def _tree(sizes):
    return {f"leaf{i}": jnp.zeros((n,), jnp.float32)
            for i, n in enumerate(sizes)}


def test_plan_buckets_greedy_fill_covers_every_leaf_once():
    tree = _tree([100, 200, 300, 50, 400])
    plan = overlap_lib.plan_buckets(tree, bucket_mb=0.001)  # 1000 bytes
    seen = sorted(i for bucket in plan.buckets for i in bucket)
    assert seen == list(range(5))
    assert plan.num_leaves == 5
    assert plan.total_bytes == sum(
        leaf.size * 4 for leaf in jax.tree_util.tree_leaves(tree)
    )
    # Greedy fill in tree_leaves order: no bucket except the last closes
    # below the threshold unless the next leaf would overflow it.
    for bucket, nbytes in zip(plan.buckets[:-1], plan.bucket_bytes[:-1]):
        assert nbytes + 50 * 4 >= plan.bucket_mb * 1e6 or len(bucket) >= 1


def test_plan_buckets_oversized_leaf_gets_own_bucket():
    tree = _tree([10, 5000, 10])
    plan = overlap_lib.plan_buckets(tree, bucket_mb=0.001)
    big = [b for b in plan.buckets if 1 in b]
    assert big == [[1]] or big == [(1,)] or list(big[0]) == [1]


def test_plan_buckets_nonpositive_mb_single_bucket():
    tree = _tree([100, 200, 300])
    plan = overlap_lib.plan_buckets(tree, bucket_mb=0)
    assert plan.num_buckets == 1
    assert sorted(plan.buckets[0]) == [0, 1, 2]


def test_plan_buckets_describe_shape():
    plan = overlap_lib.plan_buckets(_tree([256, 256]), bucket_mb=4.0)
    d = plan.describe()
    assert set(d) >= {"num_buckets", "num_leaves", "bucket_mb", "total_mb"}
    assert d["num_leaves"] == 2


def test_scheduled_leaf_map_applies_fn_per_leaf():
    tree = _tree([64, 128, 256])
    plan = overlap_lib.plan_buckets(tree, bucket_mb=0.0005)
    out = overlap_lib.scheduled_leaf_map(
        lambda i, leaf: leaf + float(i), tree, plan
    )
    leaves = jax.tree_util.tree_leaves(out)
    for i, leaf in enumerate(leaves):
        np.testing.assert_allclose(np.asarray(leaf), float(i))


def test_scheduled_leaf_map_rejects_mismatched_tree():
    plan = overlap_lib.plan_buckets(_tree([64, 128]), bucket_mb=1.0)
    with pytest.raises(ValueError):
        overlap_lib.scheduled_leaf_map(
            lambda i, leaf: leaf, _tree([64, 128, 256]), plan
        )


def test_ordered_after_is_value_identity():
    vals = [jnp.arange(4.0), jnp.ones((2, 2))]
    out = overlap_lib.ordered_after(vals, jnp.zeros(()))
    assert len(out) == len(vals)
    for got, want in zip(out, vals):
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


# ---------------------------------------------------------------------------
# overlapped-vs-serialized end-to-end parity
# ---------------------------------------------------------------------------


def _build(overlap, data, fsdp, grad_accum=1, reduce_quant="none",
           allgather_quant="none"):
    mesh = build_mesh(ParallelConfig(data=data, fsdp=fsdp))
    model = TransformerLM(TINY)
    # SGD is linear in the gradient: parity isolates the collective
    # schedule instead of compounding through Adam moments.
    opt = train_lib.make_optimizer("sgd", learning_rate=1e-2)
    return train_lib.build_sharded_train(
        model, opt, mesh, lr.DEFAULT_RULES,
        global_batch_size=32, seq_len=16,
        grad_accum=grad_accum, reduce_quant=reduce_quant, zero1=True,
        overlap=overlap, overlap_bucket_mb=0.2,
        allgather_quant=allgather_quant,
    )


def _batch(train, seed):
    rng = np.random.default_rng(seed)
    tokens = rng.integers(0, 256, size=(32, 17), dtype=np.int32)
    return train_lib.shard_batch(
        {"inputs": tokens[:, :-1], "targets": tokens[:, 1:]}, train
    )


def _run_steps(train, steps=3):
    state = train.init(jax.random.PRNGKey(0))
    state, metrics = train.step(state, _batch(train, 0))  # pays the trace
    with trace_asserts.assert_no_retrace("train_step"):
        for seed in range(1, steps):
            state, metrics = train.step(state, _batch(train, seed))
    jax.block_until_ready(metrics["loss"])
    return state, float(metrics["loss"])


def _flat_params(state):
    return np.concatenate([
        np.asarray(leaf, dtype=np.float64).ravel()
        for leaf in jax.tree_util.tree_leaves(state.params)
    ])


@pytest.mark.parametrize(
    "data,fsdp,grad_accum",
    [
        (4, 2, 2),
        # Extra mesh shapes compile two more full builds each (~15s on the
        # 1-core CI box); dp4-ga2 stays as the tier-1 witness.
        pytest.param(4, 2, 1, marks=pytest.mark.slow),
        pytest.param(2, 4, 2, marks=pytest.mark.slow),
    ],
    ids=["dp4-ga2", "dp4-ga1", "dp2-ga2"],
)
def test_overlap_matches_serialized(data, fsdp, grad_accum):
    """Scan-interior per-bucket reduce-scatter + per-bucket all-gather
    lands on the same params as the serialized end-of-step chain — the
    linearity invariant the whole engine rests on — with zero
    steady-state retraces on either build."""
    if len(jax.devices()) < data * fsdp:
        pytest.skip("needs the virtual multi-device mesh")
    serial_state, serial_loss = _run_steps(
        _build(False, data, fsdp, grad_accum)
    )
    overlap_state, overlap_loss = _run_steps(
        _build(True, data, fsdp, grad_accum)
    )
    assert np.isfinite(serial_loss) and np.isfinite(overlap_loss)
    np.testing.assert_allclose(
        _flat_params(overlap_state), _flat_params(serial_state),
        rtol=PARITY_RTOL, atol=PARITY_ATOL,
    )


@pytest.mark.slow  # second full overlap build, ~25s; the int8 wire is
# graded directly in test_quantized_collectives, the overlap schedule by
# test_overlap_matches_serialized above.
def test_overlap_int8_transports_match_within_quant_tolerance():
    """int8 reduce-scatter per microbatch + int8 re-replication
    all-gather: one quantization round per leg, so the bound scales with
    grad_accum but stays small for gradient-sized values."""
    if len(jax.devices()) < 8:
        pytest.skip("needs the virtual multi-device mesh")
    serial_state, _ = _run_steps(
        _build(False, 4, 2, grad_accum=2, reduce_quant="int8")
    )
    overlap_state, _ = _run_steps(
        _build(True, 4, 2, grad_accum=2, reduce_quant="int8",
               allgather_quant="int8")
    )
    np.testing.assert_allclose(
        _flat_params(overlap_state), _flat_params(serial_state),
        rtol=INT8_RTOL, atol=INT8_ATOL,
    )


def test_overlap_build_reports_plan():
    """The ShardedTrain handle carries the bucket plan the build used —
    what the overlap bench books as ``bucket_plan``."""
    if len(jax.devices()) < 8:
        pytest.skip("needs the virtual multi-device mesh")
    train = _build(True, 4, 2, grad_accum=2)
    assert train.overlap
    plan = train.overlap_plan
    assert plan is not None and plan["num_buckets"] >= 2
    serial = _build(False, 4, 2, grad_accum=2)
    assert serial.overlap_plan is None
