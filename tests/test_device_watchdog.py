"""Device-init watchdog: a trainer that hangs below Python before its
first step (wedged device relay / PJRT init) must be restarted and, when
the hang persists, failed — instead of heartbeating healthily forever.

VERDICT r4 #2b.  The reference's hang detection
(``check_training_hang_operator.py:26-60``) only covers the stepping
case; the pre-first-step window is TPU-specific (remote relay init).
"""

import os
import sys
import time

import pytest

from dlrover_tpu.agent.training_agent import (
    ElasticAgent,
    ElasticLaunchConfig,
    RunResult,
)
from dlrover_tpu.master.job_master import JobMaster

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# A "trainer" that simulates a wedged device init: alive, heartbeating at
# the process level, but never reaching a first step (no metrics write).
HANG_SCRIPT = "import time\ntime.sleep(3600)\n"

# A trainer whose device init is slow but healthy: writes the metrics
# file (the first-step evidence) after a delay, then exits cleanly.
SLOW_OK_SCRIPT = """
import json, os, time
time.sleep(1.0)
path = os.environ["DLROVER_TPU_METRICS_FILE"]
with open(path + ".tmp", "w") as f:
    json.dump({"device_mem_gb": 0.0, "timestamp": time.time()}, f)
os.replace(path + ".tmp", path)
time.sleep(1.0)
"""


@pytest.fixture(autouse=True)
def _isolated_dirs(monkeypatch, tmp_path):
    monkeypatch.setenv("DLROVER_TPU_SOCKET_DIR", str(tmp_path / "socks"))
    monkeypatch.setenv("DLROVER_TPU_JOB", f"wd{os.getpid()}_{tmp_path.name}")


def _agent(master_port, script, **cfg_kwargs):
    config = ElasticLaunchConfig(
        min_nodes=1, max_nodes=1,
        monitor_interval=0.2,
        heartbeat_interval=0.5,
        rdzv_timeout=30.0,
        **cfg_kwargs,
    )
    return ElasticAgent(
        config, [sys.executable, "-c", script],
        f"localhost:{master_port}", node_id=0,
    )


@pytest.mark.slow  # chaos test: hung-init restart cycles with real timeouts
def test_hung_device_init_restarts_then_fails():
    master = JobMaster(num_nodes=1, heartbeat_timeout=3600.0)
    port = master.start()
    agent = _agent(
        port, HANG_SCRIPT, device_init_timeout=1.5, max_restarts=1,
    )
    try:
        t0 = time.monotonic()
        result = agent.run()
        elapsed = time.monotonic() - t0
        # One watchdog fire -> restart; second fire -> budget exhausted ->
        # FAILED.  Without the watchdog this would hang the full 3600s.
        assert result == RunResult.FAILED
        assert elapsed < 60
        # The master heard the device-init-hang diagnosis.
        node = master.node_manager.ensure_node(0)
        assert "device-init-hang" in (node.error or "")
    finally:
        agent.shutdown()
        master.stop()


def test_slow_but_healthy_init_not_killed():
    """First-step evidence before the timeout latches the watchdog off."""
    master = JobMaster(num_nodes=1, heartbeat_timeout=3600.0)
    port = master.start()
    # Interpreter start alone is ~2 s on this image (sitecustomize imports
    # jax); the metrics write lands ~3 s after spawn, well inside 10 s.
    agent = _agent(
        port, SLOW_OK_SCRIPT, device_init_timeout=10.0, max_restarts=0,
    )
    try:
        result = agent.run()
        assert result == RunResult.SUCCEEDED
        assert agent._first_step_confirmed
    finally:
        agent.shutdown()
        master.stop()


def test_watchdog_disabled_by_zero():
    agent = ElasticAgent(
        ElasticLaunchConfig(device_init_timeout=0.0),
        ["true"], "localhost:1",
    )
    agent._worker_started_wallclock = time.time() - 10_000
    assert not agent._device_init_hung()
