"""Unified telemetry plane: recorder, wire format, job timeline, straggler
attribution, and the metrics exposition."""

import pickle
import threading
import time

import pytest

from dlrover_tpu.common import telemetry
from dlrover_tpu.common.telemetry import (
    TelemetryRecorder,
    events_to_chrome_trace,
)
from dlrover_tpu.master import messages as msg
from dlrover_tpu.master.diagnosis import (
    ActionType,
    DiagnosisContext,
    InferenceChain,
    StragglerOperator,
)
from dlrover_tpu.master.metrics import MetricsCollector
from dlrover_tpu.master.node_manager import NodeManager
from dlrover_tpu.master.servicer import MasterServicer
from dlrover_tpu.master.speed_monitor import SpeedMonitor
from dlrover_tpu.master.timeline import JobTimeline


def _recorder(**kw):
    kw.setdefault("enabled", True)
    kw.setdefault("ring_size", 256)
    return TelemetryRecorder(**kw)


# -- recorder ----------------------------------------------------------------


def test_span_nesting_and_attrs():
    r = _recorder(source="trainer")
    with r.span("outer", step=7):
        with r.span("inner", piece="a"):
            pass
    events = r.drain()
    # Inner exits (and records) first; both carry their attrs + src.
    assert [e[0] for e in events] == ["inner", "outer"]
    inner, outer = events
    assert inner[1] == "span" and inner[4]["piece"] == "a"
    assert outer[4]["step"] == 7
    assert inner[4]["src"] == outer[4]["src"] == "trainer"
    assert outer[3] >= inner[3] >= 0.0  # outer duration covers inner


def test_span_attrs_mutable_mid_span():
    r = _recorder()
    with r.span("rendezvous") as sp:
        sp.attrs["round"] = 3
    (event,) = r.drain()
    assert event[4]["round"] == 3


def test_span_records_error_kind_and_reraises():
    r = _recorder()
    with pytest.raises(ValueError):
        with r.span("step"):
            raise ValueError("boom")
    (event,) = r.drain()
    assert event[4]["error"] == "ValueError"


def test_event_duration_selects_kind():
    r = _recorder()
    r.event("restart")
    r.event("compile", duration_s=1.5)
    instant, timed = r.drain()
    assert instant[1] == "event" and instant[3] == 0.0
    assert timed[1] == "span" and timed[3] == 1.5


def test_event_t_mono_backdates():
    """Modeled sub-phases (microbatch accumulate/reduce/update) are
    recorded after their enclosing step span closes but placed at
    caller-captured times inside it."""
    r = _recorder()
    t0 = time.monotonic() - 2.5
    r.event("accumulate", duration_s=1.0, t_mono=t0, micro=0)
    r.event("accumulate", duration_s=1.0, t_mono=t0 + 1.0, micro=1)
    first, second = r.drain()
    assert first[1] == "span" and second[1] == "span"
    assert abs(second[2] - first[2] - 1.0) < 0.01
    assert first[2] < time.time() - 2.0  # backdated, not "now"


def test_reserved_attrs_rejected_with_clear_error():
    """Regression: attrs named after the span()/event() parameters used to
    surface as an opaque ``TypeError: got multiple values for argument`` —
    or, for ``duration_s`` arriving through a **dict, silently rebind the
    timing channel.  They are now rejected with a self-describing error."""
    r = _recorder()
    # `name` no longer binds the positional parameter (positional-only):
    # it reaches attrs and is rejected there with the reserved-name error.
    with pytest.raises(ValueError, match="reserved"):
        r.event("probe", **{"name": "matmul"})
    with pytest.raises(ValueError, match="reserved"):
        r.span("probe", **{"t_mono": 1.0})
    with pytest.raises(ValueError, match="reserved"):
        telemetry.event("probe", **{"name": "matmul", "host": "w0"})
    # A numeric duration_s kwarg IS the documented timing parameter (its
    # binding is indistinguishable from intent), but a non-numeric one is
    # an attr misrouted into the timing channel.
    with pytest.raises(TypeError, match="timing parameter"):
        r.event("probe", duration_s="slow")
    # The rejection fires even while disabled — a latent collision must not
    # hide until telemetry is switched on.
    off = _recorder(enabled=False)
    with pytest.raises(ValueError, match="reserved"):
        off.event("probe", **{"name": "x"})
    # Nothing landed in the ring, and legit reserved-free attrs still work.
    assert r.drain() == []
    r.event("probe", probe_duration_s=2.0, kind="block")
    (event,) = r.drain()
    assert event[4]["probe_duration_s"] == 2.0


def test_wall_clock_anchor():
    r = _recorder()
    r.event("tick")
    (event,) = r.drain()
    assert abs(event[2] - time.time()) < 5.0


def test_ring_bounded_under_threaded_churn():
    r = _recorder(ring_size=64)

    def hammer():
        for i in range(500):
            r.event("spin", i=i)

    threads = [threading.Thread(target=hammer) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(r) == 64
    assert r.dropped == 4 * 500 - 64
    assert r.drain() and len(r) == 0


def test_disabled_mode_allocates_nothing_per_event():
    r = _recorder(enabled=False)
    # span() hands out ONE cached null context — identity, not equality:
    # the disabled hot path must not allocate per call.
    assert r.span("a", x=1) is r.span("b") is telemetry._NULL_SPAN
    r.event("a", duration_s=2.0, x=1)
    with r.span("c"):
        pass
    assert len(r) == 0 and r.drain() == []


def test_env_knobs(monkeypatch):
    monkeypatch.setenv(telemetry.ENV_ENABLE, "off")
    monkeypatch.setenv(telemetry.ENV_RING, "128")
    r = TelemetryRecorder()
    assert not r.enabled and r.ring_size == 128
    monkeypatch.setenv(telemetry.ENV_ENABLE, "1")
    assert TelemetryRecorder().enabled


def test_configure_resizes_preserving_newest():
    r = _recorder(ring_size=64)
    for i in range(64):
        r.event("e", i=i)
    r.configure(ring_size=16)
    kept = [e[4]["i"] for e in r.drain()]
    assert kept == list(range(48, 64))


class _FakeClient:
    def __init__(self):
        self.batches = []

    def report_telemetry(self, events, dropped=0):
        self.batches.append((list(events), dropped))


def test_ship_drains_events_and_dropped():
    r = _recorder(ring_size=16)
    client = _FakeClient()
    assert r.ship(client) == 0 and client.batches == []  # empty: no RPC
    for i in range(20):
        r.event("e", i=i)
    assert r.ship(client) == 16
    events, dropped = client.batches[0]
    assert len(events) == 16 and dropped == 4
    assert r.dropped == 0 and len(r) == 0


# -- wire round-trip through the servicer ------------------------------------


def test_wire_round_trip_through_servicer():
    """Trainer + agent recorders drain through pickled TelemetryEvents into
    a real servicer; the merged timeline holds both tiers' streams (the
    PR's acceptance shape: step/compile spans AND rendezvous/restart)."""
    trainer = _recorder(source="trainer")
    with trainer.span("step", step=1):
        pass
    trainer.event("compile", duration_s=2.5, cached=False)
    agent = _recorder(source="agent")
    with agent.span("rendezvous") as sp:
        sp.attrs["round"] = 0
    agent.event("restart", restart_count=1)

    timeline = JobTimeline()
    servicer = MasterServicer(timeline=timeline)
    for recorder in (trainer, agent):
        wire = pickle.dumps(msg.Envelope(
            node_id=5,
            payload=msg.TelemetryEvents(5, tuple(recorder.drain())),
        ))
        response = servicer.report(msg.safe_loads(wire))
        assert response.success, response.message

    names = {e[0] for e in timeline.events(5)[5]}
    assert {"step", "compile", "rendezvous", "restart"} <= names
    assert timeline.restart_count(5) == 1
    assert [e[3] for e in timeline.spans(5, "compile")] == [2.5]
    # src lanes survived the merge.
    sources = {e[4]["src"] for e in timeline.events(5)[5]}
    assert sources == {"trainer", "agent"}


def test_servicer_timeline_and_metrics_requests():
    timeline = JobTimeline()
    timeline.record(0, "step", kind="span", duration_s=0.1,
                    attrs={"step": 1})
    servicer = MasterServicer(
        speed_monitor=SpeedMonitor(), timeline=timeline
    )
    got = servicer.get(msg.Envelope(payload=msg.TimelineRequest()))
    assert got.success and 0 in got.payload
    text = servicer.get(msg.Envelope(payload=msg.MetricsRequest()))
    assert text.success and "dlrover_goodput" in text.payload
    # No timeline wired -> degrade, don't fail.
    bare = MasterServicer()
    assert bare.get(msg.Envelope(payload=msg.MetricsRequest())).payload == ""


def test_malformed_wire_events_do_not_drop_batch():
    timeline = JobTimeline()
    timeline.add_events(0, [
        ("good", "event", 0.0, 0.0, {}),
        "garbage",
        ("short",),
        ("also-good", "span", 1.0, 0.5, {"k": 1}),
    ])
    assert [e[0] for e in timeline.events(0)[0]] == ["good", "also-good"]


# -- embed ledger + gauges ---------------------------------------------------


def test_embed_event_routes_through_servicer_into_gauges():
    """An ``embed`` telemetry event lands in the speed monitor's embed
    ledger, and the ``dlrover_embed_*`` gauges render its snapshot."""
    sm = SpeedMonitor()
    timeline = JobTimeline()
    servicer = MasterServicer(speed_monitor=sm, timeline=timeline)
    attrs = {
        "world": 4, "rows_owned": 1200, "rows_owned_max": 400,
        "lookups": 50, "rows_fetched": 9000, "reshards": 2,
        "reshard_s": 0.75, "moved_rows": 300, "spill_bytes": 4096,
        "hit_rate": 0.8, "rows_per_s": 50_000.0,
        "unknown_future_attr": 1,  # engines may grow the event
    }
    wire = pickle.dumps(msg.Envelope(
        node_id=3,
        payload=msg.TelemetryEvents(
            3, (("embed", "event", 0.0, 0.0, attrs),)
        ),
    ))
    assert servicer.report(msg.safe_loads(wire)).success
    ledger = sm.embed_ledger()
    assert ledger["rows_owned"] == 1200 and ledger["reshards"] == 2
    assert ledger["hit_rate"] == pytest.approx(0.8)
    text = timeline.render_metrics(speed_monitor=sm)
    metrics = {}
    for line in text.splitlines():
        if line and not line.startswith("#"):
            key, value = line.rsplit(" ", 1)
            metrics[key] = float(value)
    assert metrics["dlrover_embed_rows_owned"] == 1200
    assert metrics["dlrover_embed_rows_owned_max"] == 400
    assert metrics["dlrover_embed_cache_hit_rate"] == pytest.approx(0.8)
    assert metrics["dlrover_embed_lookups_total"] == 50
    assert metrics["dlrover_embed_rows_fetched_total"] == 9000
    assert metrics["dlrover_embed_reshards_total"] == 2
    assert metrics["dlrover_embed_reshard_seconds_total"] == (
        pytest.approx(0.75)
    )
    assert metrics["dlrover_embed_moved_rows_total"] == 300
    assert metrics["dlrover_embed_spill_bytes"] == 4096
    assert metrics["dlrover_embed_rows_per_s"] == 50_000


def test_instant_fault_events_route_into_counter_gauges():
    """Instant fault-plane events (retry, circuit_open, replica.death,
    process_exit, worker_start) bump timeline counters and render as
    HELP'd ``dlrover_*_total`` gauges — the TEL001 telemetry contract:
    no emitted event kind may die unrouted in the servicer."""
    sm = SpeedMonitor()
    timeline = JobTimeline()
    servicer = MasterServicer(speed_monitor=sm, timeline=timeline)
    kinds = ("retry", "circuit_open", "replica.death", "process_exit",
             "worker_start", "worker_start")
    wire = pickle.dumps(msg.Envelope(
        node_id=1,
        payload=msg.TelemetryEvents(
            1, tuple((k, "event", 0.0, 0.0, {}) for k in kinds)
        ),
    ))
    assert servicer.report(msg.safe_loads(wire)).success
    text = timeline.render_metrics(speed_monitor=sm)
    metrics = {}
    for line in text.splitlines():
        if line and not line.startswith("#"):
            key, value = line.rsplit(" ", 1)
            metrics[key] = float(value)
    assert metrics["dlrover_retries_total"] == 1
    assert metrics["dlrover_circuit_opens_total"] == 1
    assert metrics["dlrover_replica_deaths_total"] == 1
    assert metrics["dlrover_worker_exits_total"] == 1
    assert metrics["dlrover_worker_starts_total"] == 2
    for name in ("dlrover_retries_total", "dlrover_worker_starts_total"):
        assert f"# HELP {name} " in text


def test_embed_ledger_newest_wins_max_aggregation_and_state():
    """Per-node snapshots are newest-wins; the fleet aggregate takes the
    max of plane-global counters (every reporter sees the same plane) and
    averages the per-reporter hit rate — and the ledger round-trips
    through the master-restart state snapshot."""
    sm = SpeedMonitor()
    sm.record_embed(0, rows_owned=100, hit_rate=0.5, reshards=1)
    sm.record_embed(0, rows_owned=150, hit_rate=0.6, reshards=2)  # newest
    sm.record_embed(1, rows_owned=149, hit_rate=0.8, reshards=2)
    ledger = sm.embed_ledger()
    assert ledger["reporters"] == 2 and ledger["embed_events"] == 3
    assert ledger["rows_owned"] == 150  # max, not sum: no double count
    assert ledger["reshards"] == 2
    assert ledger["hit_rate"] == pytest.approx(0.7)
    fresh = SpeedMonitor()
    fresh.restore_embed_state(sm.embed_state())
    assert fresh.embed_ledger() == ledger


def test_moe_event_routes_through_servicer_into_gauges():
    """A ``moe`` telemetry event lands in the speed monitor's router
    ledger, and the ``dlrover_moe_*`` gauges render its snapshot —
    including the per-expert load as a labeled gauge family."""
    sm = SpeedMonitor()
    timeline = JobTimeline()
    servicer = MasterServicer(speed_monitor=sm, timeline=timeline)
    attrs = {
        "step": 40, "entropy": 1.15, "drop_fraction": 0.03,
        "experts": 4, "top_k": 2,
        "load": "[0.26, 0.25, 0.25, 0.24]",
        "unknown_future_attr": 1,  # trainers may grow the event
    }
    wire = pickle.dumps(msg.Envelope(
        node_id=3,
        payload=msg.TelemetryEvents(
            3, (("moe", "event", 0.0, 0.0, attrs),)
        ),
    ))
    assert servicer.report(msg.safe_loads(wire)).success
    ledger = sm.moe_ledger()
    assert ledger["entropy"] == pytest.approx(1.15)
    assert ledger["drop_fraction"] == pytest.approx(0.03)
    assert ledger["experts"] == 4 and ledger["top_k"] == 2
    assert ledger["load"] == pytest.approx([0.26, 0.25, 0.25, 0.24])
    text = timeline.render_metrics(speed_monitor=sm)
    metrics = {}
    for line in text.splitlines():
        if line and not line.startswith("#"):
            key, value = line.rsplit(" ", 1)
            metrics[key] = float(value)
    assert metrics["dlrover_moe_gate_entropy"] == pytest.approx(1.15)
    assert metrics["dlrover_moe_capacity_drop_fraction"] == (
        pytest.approx(0.03)
    )
    assert metrics["dlrover_moe_experts"] == 4
    assert metrics["dlrover_moe_top_k"] == 2
    assert metrics["dlrover_moe_reporters"] == 1
    assert metrics['dlrover_moe_expert_load{expert="0"}'] == (
        pytest.approx(0.26)
    )
    assert metrics['dlrover_moe_expert_load{expert="3"}'] == (
        pytest.approx(0.24)
    )
    # The labeled family still carries exactly one HELP/TYPE pair.
    assert text.count("# HELP dlrover_moe_expert_load") == 1
    assert text.count("# TYPE dlrover_moe_expert_load gauge") == 1


def test_moe_ledger_newest_wins_and_aggregates():
    """Per-node router snapshots are newest-wins; the aggregate averages
    entropy/drop/load across reporters and takes the max of the geometry
    fields (every replica trains the same model)."""
    sm = SpeedMonitor()
    sm.record_moe(0, step=10, entropy=1.0, drop_fraction=0.0,
                  experts=2, top_k=1, load=[0.5, 0.5])
    sm.record_moe(0, step=20, entropy=0.6, drop_fraction=0.1,
                  experts=2, top_k=1, load=[0.8, 0.2])  # newest
    sm.record_moe(1, step=18, entropy=0.4, drop_fraction=0.3,
                  experts=2, top_k=1, load=[0.6, 0.4])
    ledger = sm.moe_ledger()
    assert ledger["moe_events"] == 3 and ledger["reporters"] == 2
    assert ledger["step"] == 20
    assert ledger["entropy"] == pytest.approx(0.5)
    assert ledger["drop_fraction"] == pytest.approx(0.2)
    assert ledger["load"] == pytest.approx([0.7, 0.3])
    # A reporter with a stale-width load vector is excluded from the
    # elementwise mean, never crashes it.
    sm.record_moe(2, experts=2, top_k=1, load=[1.0])
    assert sm.moe_ledger()["load"] == pytest.approx([0.7, 0.3])


def test_plane_emit_telemetry_books_the_stats_snapshot():
    """``ShardedEmbeddingTable.emit_telemetry`` books one ``embed`` event
    whose attrs are exactly the stats the master's ledger consumes."""
    import numpy as np

    from dlrover_tpu.embedding import ShardedEmbeddingTable

    r = telemetry.recorder()
    was = r.enabled
    r.configure(enabled=True)
    r.drain()
    plane = ShardedEmbeddingTable(
        "tele", dim=4, num_buckets=8, world=2, learning_rate=0.1, seed=1
    )
    try:
        plane.lookup(np.arange(16, dtype=np.int64))
        plane.emit_telemetry(hit_rate=0.9)
        events = [e for e in r.drain() if e[0] == "embed"]
        assert len(events) == 1
        attrs = events[0][4]
        assert attrs["world"] == 2 and attrs["rows_owned"] == 16
        assert attrs["lookups"] == 1 and attrs["hit_rate"] == 0.9
        sm = SpeedMonitor()
        sm.record_embed(0, **attrs)  # the servicer's exact call shape
        assert sm.embed_ledger()["rows_owned"] == 16
    finally:
        plane.close()
        r.configure(enabled=was)


# -- chrome trace ------------------------------------------------------------


def test_chrome_trace_tracks_per_node_and_source():
    events = {
        0: [("step", "span", 10.0, 0.25, {"src": "trainer", "step": 1}),
            ("restart", "event", 11.0, 0.0, {"src": "agent"})],
        1: [("step", "span", 10.1, 0.30, {"src": "trainer", "step": 1})],
    }
    trace = events_to_chrome_trace(events)["traceEvents"]
    slices = [e for e in trace if e["ph"] == "X"]
    instants = [e for e in trace if e["ph"] == "i"]
    assert {e["pid"] for e in slices} == {0, 1}
    assert instants[0]["pid"] == 0
    # trainer and agent get distinct thread lanes within node 0.
    node0 = {e["tid"] for e in trace if e["pid"] == 0 and e["ph"] != "M"}
    assert len(node0) == 2
    step = next(e for e in slices if e["pid"] == 0)
    assert step["dur"] == pytest.approx(0.25e6)
    assert step["args"]["step"] == 1 and "src" not in step["args"]
    names = [e for e in trace if e["ph"] == "M"]
    assert any(e["name"] == "process_name" for e in names)
    assert any(e["args"].get("name") == "agent" for e in names)


# -- skew attribution + straggler operator -----------------------------------


def _skewed_timeline(nodes=3, steps=12, slow_node=2, ratio=3.0):
    timeline = JobTimeline()
    for step in range(steps):
        for node in range(nodes):
            duration = 0.1 * ratio if node == slow_node else 0.1
            timeline.record(node, "step", kind="span", duration_s=duration,
                            attrs={"step": step})
    return timeline


def test_step_stats_and_slowest_histogram():
    timeline = _skewed_timeline()
    stats = timeline.step_stats()
    assert stats[2]["p50"] == pytest.approx(0.3)
    assert stats[0]["p95"] == pytest.approx(0.1)
    assert timeline.slowest_per_step() == {2: 12}
    assert timeline.steps_observed() == 12
    assert timeline.step_skew(2.0) == {2: 12}


def test_straggler_operator_reports_slow_node():
    ctx = DiagnosisContext(
        speed_monitor=SpeedMonitor(), metrics=None, node_manager=None,
        timeline=_skewed_timeline(),
    )
    actions = StragglerOperator().observe(ctx)
    assert len(actions) == 1
    action = actions[0]
    assert action.action == ActionType.REPORT
    assert action.node_id == 2
    assert "node 2" in action.reason and "straggler" in action.reason


def test_straggler_balanced_world_stays_quiet():
    timeline = JobTimeline()
    for step in range(20):
        for node in range(3):
            timeline.record(node, "step", kind="span",
                            duration_s=0.1 + 0.001 * node,
                            attrs={"step": step})
    ctx = DiagnosisContext(
        speed_monitor=SpeedMonitor(), metrics=None, node_manager=None,
        timeline=timeline,
    )
    assert StragglerOperator().observe(ctx) == []
    # And absent/None timeline disables the rule instead of raising.
    ctx.timeline = None
    assert StragglerOperator().observe(ctx) == []


def test_straggler_needs_persistent_evidence():
    # Below MIN_STEPS multi-node steps: no verdict yet.
    ctx = DiagnosisContext(
        speed_monitor=SpeedMonitor(), metrics=None, node_manager=None,
        timeline=_skewed_timeline(steps=StragglerOperator.MIN_STEPS - 1),
    )
    assert StragglerOperator().observe(ctx) == []


def test_straggler_registered_in_default_chain():
    assert any(
        isinstance(op, StragglerOperator)
        for op in InferenceChain().operators
    )


# -- metrics exposition ------------------------------------------------------


def test_render_metrics_overlap_fraction_gauge():
    """A calibration ledger that has observed a measured overlap fraction
    renders it as the ``dlrover_overlap_fraction`` gauge."""
    from dlrover_tpu.master.calibration import CalibrationLedger
    from dlrover_tpu.master.timeline import JobTimeline

    led = CalibrationLedger()
    led.observe("k1", "reduce_scatter", measured=0.9, modeled=1.0)
    led.observe_overlap("k1", 0.69)
    text = JobTimeline().render_metrics(calibration=led)
    assert "dlrover_overlap_fraction 0.69" in text
    # Never observed -> the gauge reads 0, not a stale or modeled value.
    bare = CalibrationLedger()
    bare.observe("k1", "reduce_scatter", measured=0.9, modeled=1.0)
    assert "dlrover_overlap_fraction 0" in JobTimeline().render_metrics(
        calibration=bare
    )


def test_render_metrics_goodput_matches_speed_monitor():
    sm = SpeedMonitor()
    now = time.time()
    for i in range(10):
        sm.collect_global_step(i + 1, now - (10 - i) * 1.0, tokens=100)
    sm.record_compile(4.2, restart=True)
    sm.record_anomaly(5, "nan@5:loss=nan")
    sm.record_anomaly(6, "loss_spike@6:loss=9.0")
    sm.record_serve(0, qps=20.0, p50_s=0.02, p95_s=0.08, occupancy=0.75,
                    slots=4, requests=50, tokens=800)
    timeline = _skewed_timeline()
    text = timeline.render_metrics(speed_monitor=sm)
    metrics = {}
    for line in text.splitlines():
        if line and not line.startswith("#"):
            key, value = line.rsplit(" ", 1)
            metrics[key] = float(value)
    # Acceptance: exposition goodput within 1% of the ledger's own value.
    assert metrics["dlrover_goodput"] == pytest.approx(
        sm.goodput(), abs=0.01
    )
    assert metrics["dlrover_global_step"] == 10
    assert metrics["dlrover_compile_seconds_total"] == pytest.approx(4.2)
    assert metrics["dlrover_restart_compile_seconds_total"] == (
        pytest.approx(4.2)
    )
    assert metrics['dlrover_numeric_anomalies_recent{kind="nan"}'] == 1
    assert (
        metrics['dlrover_numeric_anomalies_recent{kind="loss_spike"}'] == 1
    )
    assert metrics['dlrover_step_time_seconds{node="2",quantile="0.50"}'] \
        == pytest.approx(0.3)
    assert metrics['dlrover_slowest_steps_total{node="2"}'] == 12
    # Serving-plane gauges come off the serve ledger.
    assert metrics["dlrover_serve_qps"] == pytest.approx(20.0)
    assert metrics['dlrover_serve_latency_seconds{quantile="0.5"}'] == (
        pytest.approx(0.02)
    )
    assert metrics['dlrover_serve_latency_seconds{quantile="0.95"}'] == (
        pytest.approx(0.08)
    )
    assert metrics["dlrover_serve_slot_occupancy"] == pytest.approx(0.75)
    assert metrics["dlrover_serve_requests_total"] == 50
    assert metrics["dlrover_serve_tokens_total"] == 800
    assert metrics["dlrover_serve_replicas"] == 1


def test_resize_seconds_split_by_kind_gauge_parity():
    """The resize ledger splits seconds by kind (restore vs relayout);
    the exposition's labeled ``dlrover_resize_seconds_total{kind=...}``
    lines must sum to the unlabeled total — open windows included, folded
    into the kind that opened them."""
    sm = SpeedMonitor()
    now = time.time()
    sm.collect_global_step(1, now, tokens=100)
    # A classic restore-path resize window, opened then closed by the
    # next step advance.
    sm.begin_resize(reason="preempt:1")
    time.sleep(0.01)
    sm.collect_global_step(2, now + 1.0, tokens=100)
    # Two live relayouts: one clean (ms-scale), one that fell back.
    sm.record_relayout(0.004)
    sm.record_relayout(1.5, ok=False)

    ledger = sm.resize_ledger()
    assert ledger["resizes"] == 3
    assert ledger["by_reason"]["preempt:1"] == 1
    assert ledger["by_reason"]["relayout"] == 1
    assert ledger["by_reason"]["relayout_failed"] == 1
    assert ledger["by_kind"]["relayout"] == pytest.approx(0.004)
    assert ledger["by_kind"]["restore"] >= 1.5
    assert ledger["open_kind"] == ""

    timeline = JobTimeline()
    text = timeline.render_metrics(speed_monitor=sm)
    metrics = {}
    for line in text.splitlines():
        if line and not line.startswith("#"):
            key, value = line.rsplit(" ", 1)
            metrics[key] = float(value)
    labeled = (
        metrics['dlrover_resize_seconds_total{kind="restore"}']
        + metrics['dlrover_resize_seconds_total{kind="relayout"}']
    )
    assert labeled == pytest.approx(metrics["dlrover_resize_seconds_total"])
    assert metrics['dlrover_resize_seconds_total{kind="relayout"}'] == (
        pytest.approx(0.004)
    )
    assert metrics["dlrover_resizes_total"] == 3


def test_resize_open_window_folds_into_open_kind():
    """While a resize window is still open, its elapsed seconds appear in
    BOTH the unlabeled total and the opening kind's label — the parity
    invariant holds mid-resize, not just after the window closes."""
    sm = SpeedMonitor()
    now = time.time()
    sm.collect_global_step(1, now, tokens=100)
    sm.begin_resize(reason="scale", kind="restore")
    time.sleep(0.02)
    ledger = sm.resize_ledger()
    assert ledger["open_kind"] == "restore"
    assert ledger["resize_open_s"] > 0.0
    timeline = JobTimeline()
    text = timeline.render_metrics(speed_monitor=sm)
    metrics = {}
    for line in text.splitlines():
        if line and not line.startswith("#"):
            key, value = line.rsplit(" ", 1)
            metrics[key] = float(value)
    labeled = (
        metrics['dlrover_resize_seconds_total{kind="restore"}']
        + metrics['dlrover_resize_seconds_total{kind="relayout"}']
    )
    # Both totals race the open window's clock; allow scheduler slop.
    assert labeled == pytest.approx(
        metrics["dlrover_resize_seconds_total"], abs=0.05
    )
    assert metrics['dlrover_resize_seconds_total{kind="restore"}'] > 0.0


def test_render_metrics_includes_node_manager_relaunches():
    timeline = JobTimeline()
    nm = NodeManager(num_nodes=2)
    nm._nodes[1].relaunch_count = 2
    text = timeline.render_metrics(node_manager=nm)
    assert 'dlrover_node_relaunch_count{node="1"} 2' in text


# -- eviction ----------------------------------------------------------------


def test_metrics_collector_evict():
    metrics = MetricsCollector()
    metrics.collect(0, 10.0, 1.0)
    metrics.collect(1, 90.0, 2.0, timestamp=time.time() - 1000)
    metrics.evict(1)
    assert metrics.latest(1) is None
    assert metrics.nodes() == [0]
    assert metrics.stale_nodes(300.0) == []
    metrics.evict(7)  # unknown node: no-op


def test_timeline_evict_node():
    timeline = _skewed_timeline()
    timeline.record(2, "restart")
    timeline.evict_node(2)
    assert timeline.nodes() == [0, 1]
    assert timeline.restart_count(2) == 0
    assert 2 not in timeline.step_skew(2.0)
    assert 2 not in timeline.step_stats()


def test_scale_down_evicts_observability_series():
    """Regression: a node_manager-driven departure (retire) must drop the
    node's metrics + timeline series via the master's transition hook."""
    from dlrover_tpu.master.job_master import JobMaster

    master = JobMaster(num_nodes=2, auto_scale=False)
    master.metrics.collect(1, 50.0, 4.0)
    master.timeline.record(1, "step", kind="span", duration_s=0.1,
                           attrs={"step": 3})
    assert master.metrics.latest(1) and master.timeline.nodes() == [1]
    master.node_manager.retire_node(1)
    assert master.metrics.latest(1) is None
    assert master.timeline.nodes() == []
    # The scaler's retire hook path clears series the same way.
    master.metrics.collect(0, 10.0, 1.0)
    master.timeline.record(0, "step", kind="span", duration_s=0.1,
                           attrs={"step": 4})
    master._handle_node_retired(0)
    assert master.metrics.latest(0) is None
    assert master.timeline.nodes() == []


# -- pipeline-counter folding ------------------------------------------------


def test_host_blocks_fold_into_module_recorder():
    from dlrover_tpu.utils.profiler import pipeline_counters

    recorder = telemetry.recorder()
    was_enabled = recorder.enabled
    recorder.configure(enabled=True)
    recorder.drain()
    try:
        with pipeline_counters().host_block("metrics-flush", steps=(3, 4)):
            pass
        pipeline_counters().record_place(0.002)
        events = recorder.drain()
    finally:
        recorder.configure(enabled=was_enabled)
    names = [e[0] for e in events]
    assert "metrics-flush" in names and "h2d" in names
    flush = events[names.index("metrics-flush")]
    assert flush[1] == "span" and flush[4]["steps"] == (3, 4)
    assert flush[4]["kind"] == "block"
