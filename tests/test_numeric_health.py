"""Numeric health: trainer-side detector, wire format, diagnosis operator.

VERDICT r3 #6: loss-spike/NaN/grad-norm anomaly detection reported via the
step report, a NumericAnomalyOperator in the inference chain, and a chaos
test injecting a spike (ref ``atorch/atorch/utils/loss_spike_utils.py``,
``numberic_checker.py``).
"""


from dlrover_tpu.master.diagnosis import (
    ActionType,
    DiagnosisContext,
    DiagnosisManager,
    NumericAnomalyOperator,
)
from dlrover_tpu.master.metrics import MetricsCollector
from dlrover_tpu.master.node_manager import NodeManager
from dlrover_tpu.master.speed_monitor import SpeedMonitor
from dlrover_tpu.trainer.numeric_health import NumericHealthMonitor


def test_monitor_flags_nan_and_inf():
    mon = NumericHealthMonitor()
    assert mon.check(1, float("nan"))[0].kind == "nan"
    assert mon.check(2, 1.0, float("inf"))[0].kind == "nan"
    assert mon.check(3, 1.0, 0.5) == []


def test_monitor_flags_loss_spike_not_noise():
    mon = NumericHealthMonitor(min_samples=8, spike_sigma=4.0,
                               spike_ratio=1.5)
    for i in range(20):
        assert mon.check(i, 2.0 + 0.01 * (i % 3)) == []
    found = mon.check(21, 9.0)
    assert [a.kind for a in found] == ["loss_spike"]
    # the spike stayed out of the window: an immediate second spike at the
    # same level still trips
    found = mon.check(22, 9.0)
    assert [a.kind for a in found] == ["loss_spike"]


def test_monitor_spike_needs_both_tests():
    """Converged near-zero-variance loss: sigma alone would misfire on a
    +0.2 wiggle; the ratio test keeps it quiet."""
    mon = NumericHealthMonitor(min_samples=8)
    for i in range(10):
        mon.check(i, 1.0)
    assert mon.check(11, 1.2) == []  # 1.2 < 1.5 x mean


def test_monitor_flags_grad_explosion():
    mon = NumericHealthMonitor(min_samples=4, grad_ratio=10.0)
    for i in range(8):
        mon.check(i, 2.0, grad_norm=1.0)
    found = mon.check(9, 2.0, grad_norm=50.0)
    assert [a.kind for a in found] == ["grad_explosion"]


def test_warmup_never_spikes():
    mon = NumericHealthMonitor(min_samples=8)
    # early-training wildness below min_samples: silence
    for i, loss in enumerate([11.0, 8.0, 30.0, 4.0, 2.0]):
        assert mon.check(i, loss) == []


def _ctx(sm):
    return DiagnosisContext(
        speed_monitor=sm, metrics=MetricsCollector(),
        node_manager=NodeManager(num_nodes=1), hang_threshold=0.0,
    )


def test_operator_nan_restarts_world_once():
    sm = SpeedMonitor()
    sm.record_anomaly(120, "nan@120:loss=nan grad_norm=3.0")
    op = NumericAnomalyOperator()
    actions = op.observe(_ctx(sm))
    assert [a.action for a in actions] == [ActionType.RESTART_WORLD]
    assert actions[0].severity == 3
    # the SAME stale report must not restart again next tick
    assert op.observe(_ctx(sm)) == []
    # a NEW nan does
    sm.record_anomaly(180, "nan@180:loss=nan grad_norm=1.0")
    assert len(op.observe(_ctx(sm))) == 1


def test_operator_spikes_surface_as_report():
    sm = SpeedMonitor()
    sm.record_anomaly(10, "loss_spike@10:loss=9 vs window mean=2")
    op = NumericAnomalyOperator()
    assert op.observe(_ctx(sm)) == []  # one spike: below threshold
    sm.record_anomaly(15, "grad_explosion@15:grad_norm=50 vs median=1")
    actions = op.observe(_ctx(sm))
    assert [a.action for a in actions] == [ActionType.REPORT]


def test_chain_injected_spike_chaos():
    """Chaos path: a trainer reports a NaN through the servicer wire shape
    (record_anomaly) and the manager prescribes a world restart."""
    sm = SpeedMonitor()
    sm.collect_global_step(100, tokens=100)
    manager = DiagnosisManager(cooldown_s=0.0)
    assert manager.run(_ctx(sm)) == []  # healthy
    sm.record_anomaly(101, "nan@101:loss=nan grad_norm=nan")
    actions = manager.run(_ctx(sm))
    assert any(a.action == ActionType.RESTART_WORLD for a in actions)
