"""AGD / WSAM / µP optimizer family."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from dlrover_tpu.optimizers import agd, make_wsam_step, mup_config, mup_scale
from dlrover_tpu.optimizers.wsam import WSAMConfig


def _agd_reference(params, grads_seq, lr, b1, b2, delta, wd):
    """NumPy transcription of the reference AGD update (non-win branch) for
    cross-checking the optax implementation step by step."""
    p = params.copy()
    m = np.zeros_like(p)
    v = np.zeros_like(p)
    for t, g in enumerate(grads_seq, start=1):
        p = p * (1.0 - lr * wd)
        m_old = m.copy()
        m = b1 * m + (1 - b1) * g
        bc1_old = 1 - b1 ** (t - 1)
        bc1, bc2 = 1 - b1 ** t, 1 - b2 ** t
        if t == 1:
            diff = m / bc1
        else:
            diff = m / bc1 - m_old / bc1_old
        v = b2 * v + (1 - b2) * diff * diff
        denom = np.maximum(np.sqrt(v), delta * np.sqrt(bc2))
        p = p - (lr * np.sqrt(bc2) / bc1) * (m / denom)
    return p


def test_agd_matches_reference_math():
    lr, b1, b2, delta, wd = 0.01, 0.9, 0.999, 1e-5, 0.1
    rng = np.random.default_rng(0)
    p0 = rng.normal(size=(6,)).astype(np.float32)
    grads_seq = [rng.normal(size=(6,)).astype(np.float32) for _ in range(4)]

    tx = agd(lr, b1=b1, b2=b2, delta=delta, weight_decay=wd)
    params = {"w": jnp.asarray(p0)}
    state = tx.init(params)
    for g in grads_seq:
        updates, state = tx.update({"w": jnp.asarray(g)}, state, params)
        params = optax.apply_updates(params, updates)
    expected = _agd_reference(p0, grads_seq, lr, b1, b2, delta, wd)
    # The optax form folds decay into the same update (order differs by one
    # O(lr^2) term); tolerances cover that.
    np.testing.assert_allclose(params["w"], expected, rtol=2e-3, atol=2e-4)


def test_agd_converges_on_quadratic():
    tx = agd(0.1)
    params = {"w": jnp.full((4,), 5.0)}
    state = tx.init(params)
    for _ in range(200):
        grads = jax.tree.map(lambda p: 2 * p, params)  # d/dp of p^2
        updates, state = tx.update(grads, state, params)
        params = optax.apply_updates(params, updates)
    assert float(jnp.abs(params["w"]).max()) < 1e-2


def test_agd_reachable_from_make_optimizer():
    from dlrover_tpu.trainer import train_lib

    tx = train_lib.make_optimizer("agd", learning_rate=1e-3)
    params = {"w": jnp.ones((3,))}
    state = tx.init(params)
    updates, _ = tx.update({"w": jnp.ones((3,))}, state, params)
    assert jax.tree.leaves(updates)


def test_wsam_decreases_loss_and_prefers_flat_minima():
    def loss_fn(params, x):
        return jnp.mean((x @ params["w"]) ** 2)

    base = optax.sgd(0.05)
    step = jax.jit(
        make_wsam_step(
            loss_fn, base,
            WSAMConfig(rho=0.05, gamma=0.5, learning_rate=0.05),
        )
    )
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(16, 4)), jnp.float32)
    params = {"w": jnp.asarray(rng.normal(size=(4,)), jnp.float32)}
    opt_state = base.init(params)
    losses = []
    for _ in range(50):
        params, opt_state, loss = step(params, opt_state, x)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.05


def test_wsam_non_decoupled_mixes_gradients():
    def loss_fn(params):
        return jnp.sum(params["w"] ** 2)

    base = optax.sgd(0.1)
    step = make_wsam_step(
        loss_fn, base, WSAMConfig(rho=0.1, gamma=0.9, decouple=False)
    )
    params = {"w": jnp.asarray([1.0, -2.0])}
    new_params, _, loss = step(params, base.init(params))
    assert float(loss) == pytest.approx(5.0)
    # The ascent point has a larger gradient; mixed grad > clean grad, so
    # the step must be larger than plain SGD's.
    plain = params["w"] - 0.1 * 2 * params["w"]
    assert float(jnp.abs(new_params["w"]).sum()) < float(
        jnp.abs(plain).sum()
    )


def test_mup_scales_matrix_updates_only():
    tx = optax.chain(optax.sgd(1.0), mup_scale(4.0))
    params = {
        "blocks": {"mlp": {"wi": {"kernel": jnp.ones((3, 3))}}},
        "embed": {"embedding": jnp.ones((5, 3))},
        "ln_final": {"scale": jnp.ones((3,))},
    }
    grads = jax.tree.map(jnp.ones_like, params)
    updates, _ = tx.update(grads, tx.init(params), params)
    np.testing.assert_allclose(
        updates["blocks"]["mlp"]["wi"]["kernel"], -0.25
    )  # matrix-like: scaled 1/4
    np.testing.assert_allclose(updates["embed"]["embedding"], -1.0)
    np.testing.assert_allclose(updates["ln_final"]["scale"], -1.0)


def test_mup_config_sets_logit_scale():
    from dlrover_tpu.models.gpt2 import gpt2_config

    cfg = mup_config(gpt2_config("355m"), base_d_model=256)
    assert cfg.logit_scale == pytest.approx(256 / 1024)

    # The scaled logits actually flow through the model.
    small = gpt2_config(
        "124m", num_layers=1, d_model=64, num_heads=2,
        vocab_size=128, max_seq_len=16,
    )
    import dataclasses

    from dlrover_tpu.models.transformer import TransformerLM

    scaled = dataclasses.replace(small, logit_scale=0.5)
    tokens = jnp.zeros((1, 8), jnp.int32)
    m1, m2 = TransformerLM(small), TransformerLM(scaled)
    variables = m1.init(jax.random.PRNGKey(0), tokens)
    logits1, _ = m1.apply(variables, tokens)
    logits2, _ = m2.apply(variables, tokens)
    np.testing.assert_allclose(
        np.asarray(logits1) * 0.5, np.asarray(logits2), rtol=1e-5
    )
