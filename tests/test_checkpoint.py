"""Flash Checkpoint tests: IPC primitives, shm packing, engine/saver cycle.

Mirrors the reference's test approach (SURVEY.md §4:
``test_ckpt_saver.py``/``checkpoint_egine_test.py`` exercise shm handler +
saver single-node with temp dirs as storage).
"""

import os
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlrover_tpu.common import multi_process as mp_ipc
from dlrover_tpu.common.storage import (
    CheckpointDirLayout,
    KeepLatestStepStrategy,
    KeepStepIntervalStrategy,
    PosixDiskStorage,
)
from dlrover_tpu.checkpoint.checkpointer import Checkpointer, StorageType
from dlrover_tpu.checkpoint.shm_handler import SharedMemoryHandler, assemble_tensor


@pytest.fixture(autouse=True)
def _socket_dir(tmp_path, monkeypatch):
    monkeypatch.setenv("DLROVER_TPU_SOCKET_DIR", str(tmp_path / "socks"))


def test_shared_queue_lock_dict_cross_object(tmp_path):
    server_q = mp_ipc.SharedQueue("q1", create=True)
    client_q = mp_ipc.SharedQueue("q1", create=False)
    client_q.put({"step": 3})
    assert server_q.get(timeout=2) == {"step": 3}
    assert client_q.get(timeout=0.1, default="empty") == "empty"

    server_l = mp_ipc.SharedLock("l1", create=True)
    client_l = mp_ipc.SharedLock("l1", create=False)
    assert client_l.acquire()
    # Reentrant for the same owner (lost-response retries must not deadlock).
    assert client_l.acquire(blocking=False)
    # Contended from a *different* thread -> refused.
    from_other: list = []
    t = threading.Thread(
        target=lambda: from_other.append(server_l.acquire(blocking=False))
    )
    t.start(); t.join()
    assert from_other == [False]
    assert client_l.release()
    assert server_l.acquire(blocking=False)
    server_l.release()

    server_d = mp_ipc.SharedDict("d1", create=True)
    client_d = mp_ipc.SharedDict("d1", create=False)
    client_d.set("k", [1, 2])
    assert server_d.get("k") == [1, 2]
    client_d.update({"a": 1, "b": 2})
    assert set(server_d.snapshot()) == {"k", "a", "b"}
    for obj in (server_q, server_l, server_d):
        obj.close()


def test_shm_handler_roundtrip():
    handler = SharedMemoryHandler(f"t{os.getpid()}")
    state = {
        "w": jnp.arange(12, dtype=jnp.float32).reshape(3, 4),
        "b": np.ones(5, np.int32),
        "nested": {"s": jnp.float32(2.5)},
    }
    meta = handler.save_state_dict(state, step=7, extra={"note": "x"})
    assert meta.step == 7

    reader = SharedMemoryHandler(f"t{os.getpid()}")
    meta2 = reader.load_meta()
    assert meta2.step == 7 and meta2.extra == {"note": "x"}
    arrays = {
        t.path: assemble_tensor(t, lambda r: reader.load_block(meta2, r))
        for t in meta2.tensors
    }
    flat = {p: a for p, a in arrays.items()}
    w = [a for p, a in flat.items() if "'w'" in "".join(p)][0]
    np.testing.assert_array_equal(
        w, np.arange(12, dtype=np.float32).reshape(3, 4)
    )
    handler.close(unlink=True)
    reader.close()


def test_shm_handler_sharded_array():
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    devices = np.asarray(jax.devices()[:4]).reshape(4)
    mesh = Mesh(devices, ("x",))
    arr = jax.device_put(
        jnp.arange(32, dtype=jnp.float32).reshape(8, 4),
        NamedSharding(mesh, PartitionSpec("x")),
    )
    handler = SharedMemoryHandler(f"s{os.getpid()}")
    meta = handler.save_state_dict({"p": arr}, step=1)
    t = meta.tensors[0]
    assert t.global_shape == (8, 4)
    assert len(t.shards) == 4  # one block per device shard
    out = assemble_tensor(t, lambda r: handler.load_block(meta, r))
    np.testing.assert_array_equal(out, np.asarray(arr))
    handler.close(unlink=True)


def test_checkpointer_memory_and_disk_cycle(tmp_path):
    ckpt_dir = str(tmp_path / "ckpt")
    ckpt = Checkpointer(ckpt_dir, host_index=0, num_hosts=1, local_saver=True)
    state = {
        "params": {"w": jnp.ones((4, 4)) * 3.0},
        "step": jnp.int32(11),
    }
    assert ckpt.save_checkpoint(11, state, StorageType.MEMORY)
    step, loaded = ckpt.load_checkpoint(state_template=state)
    assert step == 11
    np.testing.assert_allclose(loaded["params"]["w"], np.ones((4, 4)) * 3.0)

    state["step"] = jnp.int32(12)
    state["params"]["w"] = jnp.ones((4, 4)) * 4.0
    assert ckpt.save_checkpoint(12, state, StorageType.DISK)
    assert ckpt.wait(timeout=30)
    layout = CheckpointDirLayout(ckpt_dir)
    storage = PosixDiskStorage()
    assert layout.latest_step(storage) == 12

    # A fresh process-equivalent: new Checkpointer, shm gone -> storage load.
    ckpt._engine._shm.close(unlink=True)
    ckpt2 = Checkpointer(
        str(tmp_path / "ckpt"), host_index=0, num_hosts=1, local_saver=False
    )
    # reuse the running saver's queue/lock from ckpt's local saver
    step, loaded = ckpt2._engine.load_from_storage(
        treedef=jax.tree_util.tree_structure(state)
    )
    assert step == 12
    np.testing.assert_allclose(loaded["params"]["w"], np.ones((4, 4)) * 4.0)
    ckpt.close()


def test_restore_with_resharding(tmp_path):
    """Save under one sharding, restore under another (elastic resize)."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    ckpt_dir = str(tmp_path / "ckpt")
    ckpt = Checkpointer(ckpt_dir, host_index=0, num_hosts=1, local_saver=True)
    mesh4 = Mesh(np.asarray(jax.devices()[:4]), ("x",))
    arr = jax.device_put(
        jnp.arange(16, dtype=jnp.float32).reshape(8, 2),
        NamedSharding(mesh4, PartitionSpec("x")),
    )
    assert ckpt.save_checkpoint(5, {"w": arr}, StorageType.DISK)
    assert ckpt.wait(timeout=30)

    mesh2 = Mesh(np.asarray(jax.devices()[:2]), ("x",))
    new_sharding = {"w": NamedSharding(mesh2, PartitionSpec(None, "x"))}
    step, state = ckpt.load_checkpoint(
        shardings=new_sharding, state_template={"w": arr}
    )
    assert step == 5
    assert state["w"].sharding.mesh.shape["x"] == 2
    np.testing.assert_array_equal(np.asarray(state["w"]), np.asarray(arr))
    ckpt.close()


def test_deletion_strategies(tmp_path):
    deleted = []
    keep_latest = KeepLatestStepStrategy(max_to_keep=2)
    for s in [10, 20, 30, 40]:
        keep_latest.clean_up(s, deleted.append)
    assert deleted == [10, 20]

    deleted = []
    keep_interval = KeepStepIntervalStrategy(keep_interval=100)
    for s in [50, 100, 150, 200]:
        keep_interval.clean_up(s, deleted.append)
    assert deleted == [50, 150]


def test_reader_reattaches_after_arena_growth():
    """Saver must not keep reading a stale mapping after the trainer
    recreates a larger arena (state grew between steps)."""
    name = f"g{os.getpid()}"
    writer = SharedMemoryHandler(name)
    writer.save_state_dict({"w": np.ones(8, np.float32)}, step=1)
    reader = SharedMemoryHandler(name)
    assert reader.load_meta().step == 1
    # Grow past the arena size -> writer unlinks + recreates.
    big = {"w": np.ones(1 << 19, np.float32), "v": np.ones(1 << 19)}
    writer.save_state_dict(big, step=2)
    meta = reader.load_meta()
    assert meta is not None and meta.step == 2
    writer.close(unlink=True)
    reader.close()


def test_torn_write_is_invisible():
    """A crash mid-save must not leave a valid-looking checkpoint: the
    header is zeroed during the write and only published at the end."""
    handler = SharedMemoryHandler(f"torn{os.getpid()}")
    handler.save_state_dict({"w": np.ones(4, np.float32)}, step=1)

    # Simulate death mid-write: corrupt by zeroing the header the way
    # save_state_dict does before copying blocks.
    import struct

    handler._shm.buf[:8] = struct.pack("<Q", 0)
    assert handler.load_meta() is None
    handler.close(unlink=True)


def test_saver_sigterm_persist_path(tmp_path):
    """save_shm_to_storage persists un-flushed shm (preemption path)."""
    from dlrover_tpu.checkpoint.engine import CheckpointEngine
    from dlrover_tpu.checkpoint.saver import AsyncCheckpointSaver

    ckpt_dir = str(tmp_path / "ckpt")
    saver = AsyncCheckpointSaver(ckpt_dir, host_index=0, num_hosts=1)
    # no saver.start(): simulate event loop not draining
    engine = CheckpointEngine(ckpt_dir, host_index=0, num_hosts=1)
    engine.save_to_memory(33, {"w": jnp.full((2, 2), 9.0)})
    assert saver.save_shm_to_storage()
    layout = CheckpointDirLayout(ckpt_dir)
    assert layout.latest_step(PosixDiskStorage()) == 33
    engine.close()
    saver.stop()


def test_sparse_host_ids_commit_and_restore(tmp_path, monkeypatch):
    """ADVICE high: after an elastic shrink the live hosts may be {1, 2} —
    the commit barrier must count actual done-files (not range(num_hosts)),
    the committer must be the lowest *live* host, and restore must enumerate
    the host files actually present."""
    from dlrover_tpu.checkpoint.engine import CheckpointEngine
    from dlrover_tpu.checkpoint.saver import AsyncCheckpointSaver

    ckpt_dir = str(tmp_path / "ckpt")
    savers, engines = {}, {}
    for host in (1, 2):
        savers[host] = AsyncCheckpointSaver(ckpt_dir, host_index=host)
        savers[host].set_world([1, 2])
        savers[host].start()
        engines[host] = CheckpointEngine(
            ckpt_dir, host_index=host, num_hosts=2,
            agree_step_fn=lambda c: c,
        )
    state = {"w": jnp.full((2, 2), 5.0)}
    for host in (1, 2):
        assert engines[host].save_to_storage(21, state)
    # Host 1 is the committer (lowest live id); host 2 only persists.
    assert engines[1].wait_saver(timeout=30)
    layout = CheckpointDirLayout(ckpt_dir)
    assert layout.latest_step(PosixDiskStorage()) == 21

    # Fresh-process restore: shm gone, storage globbed by actual host ids.
    for host in (1, 2):
        engines[host]._shm.close(unlink=True)
    fresh = CheckpointEngine(
        ckpt_dir, host_index=1, num_hosts=2, agree_step_fn=lambda c: c
    )
    step, loaded = fresh.load(treedef=jax.tree_util.tree_structure(state))
    assert step == 21
    np.testing.assert_allclose(loaded["w"], np.full((2, 2), 5.0))
    for host in (1, 2):
        savers[host].stop()


def test_restore_rejects_incomplete_step_and_falls_back(tmp_path):
    """ADVICE medium: a step with a missing host data file must not be
    restored from np.empty garbage — fall back to the older committed step."""
    from dlrover_tpu.checkpoint.engine import CheckpointEngine
    from dlrover_tpu.checkpoint.saver import AsyncCheckpointSaver

    ckpt_dir = str(tmp_path / "ckpt")
    saver = AsyncCheckpointSaver(ckpt_dir, host_index=0, num_hosts=1)
    saver.start()
    engine = CheckpointEngine(
        ckpt_dir, host_index=0, num_hosts=1, agree_step_fn=lambda c: c
    )
    good = {"w": jnp.full((3,), 1.0)}
    newer = {"w": jnp.full((3,), 2.0)}
    assert engine.save_to_storage(10, good)
    assert engine.wait_saver(timeout=30)
    assert engine.save_to_storage(20, newer)
    assert engine.wait_saver(timeout=30)

    layout = CheckpointDirLayout(ckpt_dir)
    os.remove(layout.data_path(20, 0, 1))
    engine._shm.close(unlink=True)
    step, loaded = engine.load_from_storage(
        treedef=jax.tree_util.tree_structure(good)
    )
    assert step == 10
    np.testing.assert_allclose(loaded["w"], np.full((3,), 1.0))
    saver.stop()


def test_world_agreed_step_overrides_newer_shm(tmp_path):
    """ADVICE medium: a surviving host whose shm holds step 30 must restore
    the world-agreed step 10 from storage, not its own newer shm."""
    from dlrover_tpu.checkpoint.engine import CheckpointEngine
    from dlrover_tpu.checkpoint.saver import AsyncCheckpointSaver

    ckpt_dir = str(tmp_path / "ckpt")
    saver = AsyncCheckpointSaver(ckpt_dir, host_index=0, num_hosts=1)
    saver.start()
    engine = CheckpointEngine(
        ckpt_dir, host_index=0, num_hosts=1, agree_step_fn=lambda c: 10
    )
    assert engine.save_to_storage(10, {"w": jnp.full((3,), 1.0)})
    assert engine.wait_saver(timeout=30)
    assert engine.save_to_memory(30, {"w": jnp.full((3,), 3.0)})
    step, loaded = engine.load(
        treedef=jax.tree_util.tree_structure({"w": jnp.zeros((3,))})
    )
    assert step == 10
    np.testing.assert_allclose(loaded["w"], np.full((3,), 1.0))
    engine._shm.close(unlink=True)
    saver.stop()


def test_lock_release_requires_owner_and_steals_from_dead(tmp_path):
    server = mp_ipc.SharedLock("ladv", create=True)
    client = mp_ipc.SharedLock("ladv", create=False)
    assert client.acquire()
    # ADVICE low: a release from a different owner (thread) is refused.
    stray: list = []
    t = threading.Thread(target=lambda: stray.append(server.release()))
    t.start(); t.join()
    assert stray == [False]
    assert server._lock.locked()
    assert client.release()
    # Dead-owner steal: lock held by a pid that no longer exists.
    assert client.acquire()
    server._owner = "999999999:1"
    other: list = []
    t = threading.Thread(
        target=lambda: other.append(server.acquire(blocking=False))
    )
    t.start(); t.join()
    assert other == [True]
    server.close()


@pytest.mark.slow
def test_flash_save_gb_scale_is_subsecond():
    """The Flash Checkpoint headline (BASELINE.md: 151s -> 0.5s saves) rests
    on the shm memcpy being fast: a ~1 GiB state must block the trainer for
    well under a second (round-2 verdict: measure it, don't assert it)."""
    import time

    state = {
        f"w{i}": np.ones((64, 1024, 1024), np.float32) for i in range(4)
    }  # 4 x 256 MiB = 1 GiB
    handler = SharedMemoryHandler(f"gb{os.getpid()}")
    try:
        handler.save_state_dict(state, step=1)  # first call sizes the arena
        t0 = time.perf_counter()
        handler.save_state_dict(state, step=2)
        dt = time.perf_counter() - t0
        gib = 2**30
        print(f"shm save of 1 GiB took {dt:.3f}s ({1 / max(dt, 1e-9):.1f} GiB/s)")
        assert dt < 1.0, f"1 GiB shm save took {dt:.2f}s (>1s)"
        meta = handler.load_meta()
        assert meta.step == 2
    finally:
        handler.close(unlink=True)


def test_forced_stop_leaves_shared_resources_open(tmp_path):
    """If the saver thread is wedged mid-persist past the forced-stop
    window, stop() must NOT close the shared queue/lock/status/shm under
    it — closing would corrupt the in-flight write or raise in the
    worker.  Leak the handles; the process is exiting anyway."""
    from dlrover_tpu.checkpoint.saver import AsyncCheckpointSaver

    saver = AsyncCheckpointSaver(
        str(tmp_path / "ckpt"), host_index=0, num_hosts=1
    )
    release = threading.Event()
    stuck = threading.Thread(target=release.wait, daemon=True)
    stuck.start()
    saver._thread = stuck  # a worker wedged inside a persist
    saver.DRAIN_TIMEOUT_S = 0.2  # instance attrs shadow the class windows
    saver.FORCED_JOIN_TIMEOUT_S = 0.2

    saver.stop()  # must return (leaking), not raise or hang

    assert stuck.is_alive()
    # The shared resources the "worker" may be holding are still usable.
    saver._status.update({"probe": 1})
    assert saver._status.get("probe") == 1
    assert saver._event_queue.get(timeout=1.0) is not None  # the EXIT event
    # Once the worker actually exits, a second stop() closes everything.
    release.set()
    stuck.join(timeout=5.0)
    saver.stop()
