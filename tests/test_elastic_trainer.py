"""ElasticTrainer façade: direct API tests (no agent/master)."""

import os

import numpy as np
import pytest

from dlrover_tpu.models.gpt2 import gpt2_config
from dlrover_tpu.trainer.elastic_trainer import ElasticTrainer, TrainerConfig


@pytest.fixture(autouse=True)
def _isolated_shm(monkeypatch, tmp_path):
    """The flash-ckpt shm arena outlives processes and is named by the job
    tag: without a unique tag, a previous run's arena (holding a newer
    step) would satisfy this test's restore."""
    monkeypatch.setenv(
        "DLROVER_TPU_JOB", f"et{os.getpid()}_{tmp_path.name}"
    )
    monkeypatch.setenv(
        "DLROVER_TPU_SOCKET_DIR", str(tmp_path / "socks")
    )


def _tiny_model():
    return gpt2_config(
        "124m", num_layers=1, d_model=64, num_heads=2,
        vocab_size=256, max_seq_len=32,
    )


def _loader(batches, batch, seq, vocab=256, seed=0):
    rng = np.random.default_rng(seed)
    for _ in range(batches):
        toks = rng.integers(0, vocab, size=(batch, seq + 1), dtype=np.int32)
        yield {"inputs": toks[:, :-1], "targets": toks[:, 1:]}


def test_fit_trains_and_reports(tmp_path):
    seen = []
    trainer = ElasticTrainer(
        _tiny_model(),
        TrainerConfig(
            global_batch_size=8, seq_len=32, learning_rate=1e-2,
            checkpoint_dir=str(tmp_path / "ckpt"), ckpt_every=4,
            report_every=2,
        ),
        client=None,
    )
    final = trainer.fit(
        _loader(20, 8, 32), max_steps=10,
        on_step=lambda step, m: seen.append(step),
    )
    trainer.close()
    assert final == 10
    assert seen == list(range(1, 11))


def test_resume_continues_from_committed_step(tmp_path):
    ckpt = str(tmp_path / "ckpt")
    cfg = TrainerConfig(
        global_batch_size=8, seq_len=32, learning_rate=1e-2,
        checkpoint_dir=ckpt, ckpt_every=5,
    )
    first = ElasticTrainer(_tiny_model(), cfg, client=None)
    first.fit(_loader(20, 8, 32), max_steps=10)
    first.close()

    second = ElasticTrainer(_tiny_model(), cfg, client=None)
    assert second.step == 10  # restored
    final = second.fit(_loader(20, 8, 32, seed=1), max_steps=14)
    second.close()
    assert final == 14

    # A third trainer resuming AT max_steps must still re-commit its state
    # under its own world (the chaos-test regression).
    third = ElasticTrainer(_tiny_model(), cfg, client=None)
    assert third.step == 14
    assert third.fit(_loader(2, 8, 32), max_steps=14) == 14
    third.close()
    from dlrover_tpu.common.storage import (
        CheckpointDirLayout,
        PosixDiskStorage,
    )

    assert CheckpointDirLayout(ckpt).latest_step(PosixDiskStorage()) == 14
