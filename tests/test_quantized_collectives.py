"""Quantized collectives: int8 all-reduce inside shard_map and the
quantized Local-SGD delta transport."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from dlrover_tpu.parallel.quantized_collectives import (
    _block_dequant,
    _block_quant,
    a2a_wire_bytes,
    quantized_all_gather,
    quantized_all_reduce,
    quantized_all_to_all,
)
from dlrover_tpu.runtime.mesh import (
    ParallelConfig,
    build_mesh,
    shard_map_compat,
)


def test_block_quant_roundtrip_error_bound():
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(4096,)), jnp.float32)
    q, s = _block_quant(x, 256)
    back = _block_dequant(q, s, 256)
    # Symmetric absmax int8: error <= scale/2 = absmax/254 per block.
    per_block_bound = (
        np.abs(np.asarray(x).reshape(-1, 256)).max(axis=1) / 254.0
    )
    err = np.abs(np.asarray(back - x)).reshape(-1, 256).max(axis=1)
    assert (err <= per_block_bound + 1e-7).all()


def test_quantized_all_reduce_matches_psum_mean():
    if len(jax.devices()) < 4:
        pytest.skip("needs the virtual multi-device mesh")
    mesh = build_mesh(ParallelConfig(data=4, fsdp=2))
    rng = np.random.default_rng(1)
    # 700 elements: exercises the non-divisible padding path.
    x = jnp.asarray(rng.normal(size=(4, 700)), jnp.float32)

    @functools.partial(
        shard_map_compat, mesh=mesh,
        in_specs=P("data", None), out_specs=P("data", None),
    )
    def reduce(block):
        out = quantized_all_reduce(block[0], "data", block=256)
        return out[None]

    got = np.asarray(reduce(x))
    want = np.asarray(jnp.mean(x, axis=0))
    # Every member holds the same reduced value...
    for row in got:
        np.testing.assert_array_equal(row, got[0])
    # ...and it matches the exact mean within two quantization rounds.
    np.testing.assert_allclose(got[0], want, atol=0.05, rtol=0.05)


def test_quantized_all_reduce_single_member_is_identity():
    if len(jax.devices()) < 2:
        pytest.skip("needs >=2 devices")
    mesh = build_mesh(ParallelConfig(data=1, fsdp=len(jax.devices())))
    x = jnp.arange(512.0)

    @functools.partial(
        shard_map_compat, mesh=mesh, in_specs=P(), out_specs=P(),
    )
    def reduce(v):
        return quantized_all_reduce(v, "data", block=256)

    np.testing.assert_array_equal(np.asarray(reduce(x)), np.asarray(x))


@pytest.mark.parametrize("size", [700, 513, 256, 3, 1])
def test_quantized_all_reduce_partial_blocks(size):
    """Leaves whose flat size is not a multiple of the quant block (or of
    the member count) pad-and-mask instead of erroring — gradient pytrees
    hand this path biases (tiny), norms (odd), and full matrices alike."""
    if len(jax.devices()) < 4:
        pytest.skip("needs the virtual multi-device mesh")
    mesh = build_mesh(ParallelConfig(data=4, fsdp=2))
    rng = np.random.default_rng(size)
    x = jnp.asarray(rng.normal(size=(4, size)), jnp.float32)

    @functools.partial(
        shard_map_compat, mesh=mesh,
        in_specs=P("data", None), out_specs=P("data", None),
    )
    def reduce(block):
        return quantized_all_reduce(block[0], "data", block=256)[None]

    got = np.asarray(reduce(x))
    want = np.asarray(jnp.mean(x, axis=0))
    assert got.shape == x.shape
    np.testing.assert_allclose(got[0], want, atol=0.06, rtol=0.06)


def test_quantized_all_reduce_preserves_dtype():
    """bf16 gradient leaves come back bf16 (and the original shape): the
    deferred-reduce caller feeds whatever dtype the accumulator used."""
    if len(jax.devices()) < 4:
        pytest.skip("needs the virtual multi-device mesh")
    mesh = build_mesh(ParallelConfig(data=4, fsdp=2))
    rng = np.random.default_rng(7)
    x = jnp.asarray(rng.normal(size=(4, 5, 70)), jnp.bfloat16)

    @functools.partial(
        shard_map_compat, mesh=mesh,
        in_specs=P("data", None, None), out_specs=P("data", None, None),
    )
    def reduce(block):
        return quantized_all_reduce(block[0], "data", block=256)[None]

    got = reduce(x)
    assert got.dtype == jnp.bfloat16
    assert got.shape == x.shape
    want = np.asarray(jnp.mean(x.astype(jnp.float32), axis=0))
    np.testing.assert_allclose(
        np.asarray(got, np.float32)[0], want, atol=0.08, rtol=0.08,
    )


def _run_gather(x, algo, dim=0, block=256):
    """Drive quantized_all_gather over the data axis; every member's
    gathered copy comes back stacked on a leading member axis."""
    mesh = build_mesh(ParallelConfig(data=4, fsdp=2))
    specs = P("data", *([None] * (x.ndim - 1)))

    @functools.partial(
        shard_map_compat, mesh=mesh, in_specs=specs, out_specs=specs,
    )
    def gather(shard):
        out = quantized_all_gather(
            shard[0], "data", dim=dim, block=block, algo=algo
        )
        return out[None]

    return gather(x)


@pytest.mark.parametrize("algo", ["oneshot", "ring"])
def test_quantized_all_gather_error_bound(algo):
    """Gathered shards land in member order within the per-block int8
    bound; every member holds the identical full tensor."""
    if len(jax.devices()) < 4:
        pytest.skip("needs the virtual multi-device mesh")
    rng = np.random.default_rng(11)
    x = jnp.asarray(rng.normal(size=(4, 128, 4)), jnp.float32)
    got = np.asarray(_run_gather(x, algo))
    want = np.asarray(x).reshape(512, 4)  # concat of shards in axis order
    assert got.shape == (4, 512, 4)
    for member in got[1:]:
        np.testing.assert_array_equal(member, got[0])
    np.testing.assert_allclose(got[0], want, atol=0.05, rtol=0.05)


def test_quantized_all_gather_partial_final_block():
    """Shards whose flat size is not a multiple of the quant block pad
    at the source and slice after dequant — no wraparound garbage in the
    final partial block."""
    if len(jax.devices()) < 4:
        pytest.skip("needs the virtual multi-device mesh")
    rng = np.random.default_rng(13)
    x = jnp.asarray(rng.normal(size=(4, 700)), jnp.float32)  # 700 % 256 != 0
    got = np.asarray(_run_gather(x, "oneshot"))
    want = np.asarray(x).reshape(-1)
    assert got.shape == (4, 2800)
    np.testing.assert_allclose(got[0], want, atol=0.05, rtol=0.05)


def test_quantized_all_gather_preserves_bf16():
    """bf16 params come back bf16 with the gathered shape — the ZeRO-1
    re-replication caller feeds whatever dtype the params use."""
    if len(jax.devices()) < 4:
        pytest.skip("needs the virtual multi-device mesh")
    rng = np.random.default_rng(17)
    x = jnp.asarray(rng.normal(size=(4, 5, 70)), jnp.bfloat16)
    got = _run_gather(x, "ring")
    assert got.dtype == jnp.bfloat16
    assert got.shape == (4, 20, 70)
    want = np.asarray(x, np.float32).reshape(20, 70)
    np.testing.assert_allclose(
        np.asarray(got, np.float32)[0], want, atol=0.08, rtol=0.08,
    )


def test_quantized_all_gather_oneshot_ring_bitwise_parity():
    """The shard is quantized ONCE at the source, so the one-shot and
    ring transports dequantize to bit-identical tensors — algo choice is
    a topology decision, never a numerics decision."""
    if len(jax.devices()) < 4:
        pytest.skip("needs the virtual multi-device mesh")
    rng = np.random.default_rng(19)
    x = jnp.asarray(rng.normal(size=(4, 300)), jnp.float32)
    oneshot = np.asarray(_run_gather(x, "oneshot"))
    ring = np.asarray(_run_gather(x, "ring"))
    np.testing.assert_array_equal(oneshot, ring)


def test_quantized_all_gather_nonzero_dim():
    """dim=1 gather concatenates along the second axis in member order."""
    if len(jax.devices()) < 4:
        pytest.skip("needs the virtual multi-device mesh")
    rng = np.random.default_rng(23)
    x = jnp.asarray(rng.normal(size=(4, 3, 80)), jnp.float32)
    got = np.asarray(_run_gather(x, "oneshot", dim=1))
    assert got.shape == (4, 3, 320)
    want = np.concatenate(list(np.asarray(x)), axis=1)
    np.testing.assert_allclose(got[0], want, atol=0.05, rtol=0.05)


def _run_a2a(x, *, split_axis=0, concat_axis=0, block=256, quant=True):
    """Drive an all-to-all over the data axis; each member contributes
    its leading block and the per-member results come back stacked."""
    mesh = build_mesh(ParallelConfig(data=4, fsdp=2))
    specs = P("data", *([None] * (x.ndim - 1)))

    @functools.partial(
        shard_map_compat, mesh=mesh, in_specs=specs, out_specs=specs,
    )
    def exchange(shard):
        if quant:
            out = quantized_all_to_all(
                shard[0], "data", split_axis=split_axis,
                concat_axis=concat_axis, block=block,
            )
        else:
            out = jax.lax.all_to_all(
                shard[0], "data", split_axis, concat_axis, tiled=True
            )
        return out[None]

    return exchange(x)


def test_quantized_all_to_all_matches_fp32_reference():
    """Chunk routing is bit-for-bit the tiled all_to_all's (same member
    order, same concat placement); values land within the per-block int8
    bound of the exact fp32 exchange."""
    if len(jax.devices()) < 4:
        pytest.skip("needs the virtual multi-device mesh")
    rng = np.random.default_rng(29)
    x = jnp.asarray(rng.normal(size=(4, 8, 64)), jnp.float32)
    got = np.asarray(_run_a2a(x))
    want = np.asarray(_run_a2a(x, quant=False))
    assert got.shape == want.shape
    np.testing.assert_allclose(got, want, atol=0.05, rtol=0.05)


def test_quantized_all_to_all_partial_blocks():
    """Chunks whose flat size is not a multiple of the quant block pad at
    the source and slice after dequant — no wraparound garbage."""
    if len(jax.devices()) < 4:
        pytest.skip("needs the virtual multi-device mesh")
    rng = np.random.default_rng(31)
    # per-member chunk is 70*3 = 210 elements: 210 % 256 != 0
    x = jnp.asarray(rng.normal(size=(4, 280, 3)), jnp.float32)
    got = np.asarray(_run_a2a(x))
    want = np.asarray(_run_a2a(x, quant=False))
    np.testing.assert_allclose(got, want, atol=0.05, rtol=0.05)


def test_quantized_all_to_all_preserves_bf16():
    """bf16 dispatch activations come back bf16 with the exchanged
    shape — the MoE dispatch caller feeds whatever dtype the layer
    computes in."""
    if len(jax.devices()) < 4:
        pytest.skip("needs the virtual multi-device mesh")
    rng = np.random.default_rng(37)
    x = jnp.asarray(rng.normal(size=(4, 8, 40)), jnp.bfloat16)
    got = _run_a2a(x)
    assert got.dtype == jnp.bfloat16
    assert got.shape == x.shape
    want = np.asarray(
        _run_a2a(x.astype(jnp.float32), quant=False)
    )
    np.testing.assert_allclose(
        np.asarray(got, np.float32), want, atol=0.08, rtol=0.08,
    )


def test_quantized_all_to_all_split_concat_axes():
    """split/concat on distinct nonzero axes reshapes exactly like the
    tiled reference (split dim shrinks by n, concat dim grows by n)."""
    if len(jax.devices()) < 4:
        pytest.skip("needs the virtual multi-device mesh")
    rng = np.random.default_rng(41)
    x = jnp.asarray(rng.normal(size=(4, 8, 12)), jnp.float32)
    got = np.asarray(_run_a2a(x, split_axis=1, concat_axis=0))
    want = np.asarray(_run_a2a(x, split_axis=1, concat_axis=0, quant=False))
    assert got.shape == want.shape
    np.testing.assert_allclose(got, want, atol=0.05, rtol=0.05)


def test_quantized_all_to_all_involution_roundtrip():
    """With split_axis == concat_axis a second exchange routes every
    chunk home — the MoE dispatch-out/combine-back pair.  Two a2a legs =
    two quantization rounds of error, nothing more."""
    if len(jax.devices()) < 4:
        pytest.skip("needs the virtual multi-device mesh")
    mesh = build_mesh(ParallelConfig(data=4, fsdp=2))
    rng = np.random.default_rng(43)
    x = jnp.asarray(rng.normal(size=(4, 8, 16)), jnp.float32)

    @functools.partial(
        shard_map_compat, mesh=mesh,
        in_specs=P("data", None, None), out_specs=P("data", None, None),
    )
    def roundtrip(shard):
        mid = quantized_all_to_all(shard[0], "data", block=64)
        return quantized_all_to_all(mid, "data", block=64)[None]

    got = np.asarray(roundtrip(x))
    np.testing.assert_allclose(got, np.asarray(x), atol=0.1, rtol=0.1)


def test_quantized_all_to_all_single_member_is_identity():
    """Axis size 1: no wire, no quantization — bit-exact passthrough."""
    if len(jax.devices()) < 2:
        pytest.skip("needs >=2 devices")
    mesh = build_mesh(ParallelConfig(data=1, fsdp=len(jax.devices())))
    x = jnp.arange(24.0).reshape(4, 6)

    @functools.partial(
        shard_map_compat, mesh=mesh, in_specs=P(), out_specs=P(),
    )
    def exchange(v):
        return quantized_all_to_all(v, "data", block=8)

    np.testing.assert_array_equal(np.asarray(exchange(x)), np.asarray(x))


def test_quantized_all_to_all_indivisible_split_raises():
    if len(jax.devices()) < 4:
        pytest.skip("needs the virtual multi-device mesh")
    x = jnp.zeros((4, 6, 3))  # 6 % 4 != 0
    with pytest.raises(ValueError, match="must divide"):
        _run_a2a(x)


def test_a2a_wire_bytes_int8_strictly_cheaper():
    """The modeled int8 leg undercuts fp32 at every payload size — the
    pricing invariant the MoE gate certifies."""
    # (a 1-element leg is the one place the 4 B block scale loses; real
    # dispatch payloads are token*d_model-sized)
    for n in (2, 3, 255, 256, 257, 1 << 16):
        assert a2a_wire_bytes(n, "int8") < a2a_wire_bytes(n, "none")
    # exact forms: 1 B/elem + 4 B/block vs 4 B/elem
    assert a2a_wire_bytes(512, "int8", block=256) == 512 + 2 * 4
    assert a2a_wire_bytes(512, "none") == 2048


def test_local_sgd_quantized_transport_single_host():
    """In a one-process world the transport takes the exact early exit
    (nothing to compress); the quantized-comm outer loop stays exact."""
    from dlrover_tpu.parallel.local_sgd import LocalSGD, LocalSGDConfig
    from dlrover_tpu.parallel.quantized_collectives import (
        quantized_process_allgather,
    )

    tree = {"w": jnp.asarray(np.random.default_rng(2).normal(size=(300,)),
                             jnp.float32)}
    out = quantized_process_allgather(tree, block=128)
    assert len(out) == 1
    np.testing.assert_array_equal(out[0]["w"], tree["w"])

    outer = LocalSGD(LocalSGDConfig(
        sync_every=2, outer_momentum=0.0, quantized_comm=True,
    ))
    params = {"w": jnp.zeros((300,))}
    outer.init(params)
    params, _ = outer.maybe_sync({"w": jnp.full((300,), 0.5)})
    params, synced = outer.maybe_sync({"w": jnp.full((300,), 1.0)})
    assert synced
    np.testing.assert_allclose(params["w"], 1.0, atol=1e-6)


def test_quantized_transport_multi_host_payload_roundtrip():
    """The lossy wire path itself (quant -> gather -> dequant per host),
    exercised without a multi-process world by driving the payload
    transform directly."""
    from dlrover_tpu.parallel.quantized_collectives import (
        _block_dequant,
        _block_quant,
    )

    rng = np.random.default_rng(3)
    delta = jnp.asarray(rng.normal(size=(300,)), jnp.bfloat16)
    flat = jnp.asarray(delta, jnp.float32).reshape(-1)
    padded = -(-flat.size // 128) * 128
    q, s = _block_quant(jnp.pad(flat, (0, padded - flat.size)), 128)
    back = _block_dequant(q, s, 128)[: flat.size].astype(jnp.bfloat16)
    assert back.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(back, np.float32), np.asarray(delta, np.float32),
        atol=0.06,
    )
