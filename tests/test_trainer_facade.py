"""ElasticTrainer façade parity: callbacks, eval loop, LR schedule, epoch
accounting, splitter family, text shard reader.

VERDICT r3 #8/#10 (ref ``atorch/atorch/trainer/atorch_trainer.py:136``
callbacks/eval/schedules; ``dlrover/python/master/shard/
dataset_splitter.py:144-357`` table/text splitters).
"""

import os

import numpy as np
import pytest

import jax.numpy as jnp

from dlrover_tpu.master.messages import DatasetShardParams
from dlrover_tpu.master.task_manager import (
    DatasetManager,
    StreamingDatasetSplitter,
    TableDatasetSplitter,
    TextDatasetSplitter,
    make_splitter,
)
from dlrover_tpu.data.text_shards import TextShardReader
from dlrover_tpu.models.gpt2 import gpt2_config
from dlrover_tpu.trainer.elastic_trainer import (
    ElasticTrainer,
    TrainerCallback,
    TrainerConfig,
)

BATCH, SEQ = 8, 32


@pytest.fixture(autouse=True)
def _isolated_shm(monkeypatch, tmp_path):
    """The flash-ckpt shm arena outlives processes and is named by the job
    tag: without a unique tag, a previous run's arena (holding a newer
    step) would satisfy this test's restore."""
    monkeypatch.setenv("DLROVER_TPU_JOB", f"tf{os.getpid()}_{tmp_path.name}")
    monkeypatch.setenv("DLROVER_TPU_SOCKET_DIR", str(tmp_path / "socks"))


def _tiny_trainer(tmp_path=None, **cfg_kwargs):
    model_config = gpt2_config(
        "124m", num_layers=2, d_model=64, num_heads=2, vocab_size=128,
        max_seq_len=SEQ, param_dtype=jnp.float32,
    )
    cfg = TrainerConfig(
        global_batch_size=BATCH, seq_len=SEQ, learning_rate=1e-2,
        checkpoint_dir=str(tmp_path) if tmp_path else "",
        ckpt_every=1000, report_every=2, **cfg_kwargs,
    )
    return ElasticTrainer(model_config, cfg, client=False or None)


def _batches(n, seed=0):
    rng = np.random.default_rng(seed)
    for _ in range(n):
        toks = rng.integers(0, 128, size=(BATCH, SEQ + 1), dtype=np.int32)
        yield {"inputs": toks[:, :-1].copy(), "targets": toks[:, 1:].copy()}


class Recorder(TrainerCallback):
    def __init__(self):
        self.events = []

    def on_train_begin(self, trainer):
        self.events.append("begin")

    def on_step_end(self, trainer, step, metrics):
        self.events.append(("step", step))

    def on_evaluate(self, trainer, step, eval_metrics):
        self.events.append(("eval", step, eval_metrics["eval_loss"]))

    def on_epoch_end(self, trainer, epoch):
        self.events.append(("epoch", epoch))

    def on_train_end(self, trainer, step):
        self.events.append(("end", step))


@pytest.mark.slow  # multi-epoch fit loop e2e
def test_fit_with_callbacks_eval_and_lr():
    trainer = _tiny_trainer(
        warmup_steps=4, decay_steps=20, eval_every=3, eval_batches=2,
        numeric_checks=True,
    )
    recorder = Recorder()
    trainer.callbacks.append(recorder)
    lr_start = trainer.current_lr()
    assert lr_start == 0.0  # warmup starts at zero
    final = trainer.fit(
        _batches(8), max_steps=8,
        eval_loader=list(_batches(3, seed=1)),
    )
    assert final == 8
    kinds = [e if isinstance(e, str) else e[0] for e in recorder.events]
    assert kinds[0] == "begin" and kinds[-1] == "end"
    assert kinds.count("step") == 8
    evals = [e for e in recorder.events if e[0] == "eval"]
    assert len(evals) == 2  # steps 3 and 6
    assert all(np.isfinite(e[2]) for e in evals)
    # warmup climbed the schedule
    assert trainer.current_lr() > lr_start


@pytest.mark.slow  # multi-epoch fit loop e2e
def test_fit_epochs_and_resume_accounting(tmp_path):
    trainer = _tiny_trainer(tmp_path=tmp_path)
    recorder = Recorder()
    trainer.callbacks.append(recorder)
    data = list(_batches(3))
    trainer.fit(data, max_steps=6, epochs=2)
    assert trainer.step == 6
    assert trainer.epoch == 2
    epochs = [e for e in recorder.events if e[0] == "epoch"]
    assert [e[1] for e in epochs] == [1, 2]
    trainer.close()

    # A resumed trainer picks the epoch up from the restored step.
    resumed = _tiny_trainer(tmp_path=tmp_path)
    assert resumed.step == 6
    resumed.fit(data, max_steps=9, epochs=3)
    assert resumed.step == 9
    assert resumed.epoch >= 3
    resumed.close()


def test_evaluate_standalone():
    trainer = _tiny_trainer()
    out = trainer.evaluate(list(_batches(4, seed=3)), max_batches=2)
    assert out["eval_batches"] == 2
    assert np.isfinite(out["eval_loss"]) and out["eval_ppl"] > 0


# ---------------------------------------------------------------------------
# Splitter family + text shards
# ---------------------------------------------------------------------------


def test_make_splitter_maps_storage_types():
    base = dict(dataset_name="d", dataset_size=100, shard_size=10)
    assert isinstance(
        make_splitter(DatasetShardParams(storage_type="table", **base)),
        TableDatasetSplitter,
    )
    assert isinstance(
        make_splitter(DatasetShardParams(storage_type="text", **base)),
        TextDatasetSplitter,
    )
    assert isinstance(
        make_splitter(DatasetShardParams(storage_type="stream", **base)),
        StreamingDatasetSplitter,
    )


def test_text_splitter_shards_roundtrip_through_checkpoint():
    params = DatasetShardParams(
        dataset_name="corpus", dataset_size=25, shard_size=10,
        storage_type="text", num_epochs=1,
    )
    manager = DatasetManager(make_splitter(params))
    first = manager.get_task(node_id=0)
    assert (first.start, first.end) == (0, 10)
    # Checkpoint with one shard in flight + two pending; restore requeues all.
    state = manager.checkpoint()
    restored = DatasetManager(make_splitter(params))
    restored.restore(state)
    ranges = sorted(
        (t.start, t.end) for t in restored.pending
    )
    assert ranges == [(0, 10), (10, 20), (20, 25)]  # short tail shard kept


def test_text_shard_reader_reads_ranges(tmp_path):
    path = tmp_path / "corpus.txt"
    lines = [f"line-{i}" for i in range(25)]
    path.write_text("\n".join(lines) + "\n")
    reader = TextShardReader(str(path))
    assert reader.num_lines == 25
    assert reader.read_shard(0, 3) == ["line-0", "line-1", "line-2"]
    assert reader.read_shard(20, 30) == [f"line-{i}" for i in range(20, 25)]
    assert reader.read_shard(25, 30) == []
    reader.close()
    # index is cached and reused
    reader2 = TextShardReader(str(path))
    assert reader2.read_shard(10, 12) == ["line-10", "line-11"]
    reader2.close()
    # stale index (file grew) is rebuilt
    with open(path, "a") as f:
        f.write("line-25\n")
    reader3 = TextShardReader(str(path))
    assert reader3.num_lines == 26
    assert reader3.read_shard(25, 26) == ["line-25"]
    reader3.close()


def test_text_reader_drives_table_shards_end_to_end(tmp_path):
    """Master splits by line ranges; the worker reads exactly those lines."""
    path = tmp_path / "data.txt"
    path.write_text("".join(f"sample {i}\n" for i in range(40)))
    reader = TextShardReader(str(path))
    params = DatasetShardParams(
        dataset_name="d", dataset_size=reader.num_lines, shard_size=16,
        storage_type="text",
    )
    manager = DatasetManager(make_splitter(params))
    seen = []
    while True:
        task = manager.get_task(node_id=0)
        if task.task_id < 0:
            break
        seen.extend(reader.read_shard(task.start, task.end))
        manager.report_task(task.task_id, success=True)
    assert seen == [f"sample {i}" for i in range(40)]
    assert manager.finished()
    reader.close()


def test_one_shot_generator_with_epochs_raises():
    """A generator exhausted after its first pass must not let the epoch
    counter spin to N while training one epoch of data (ADVICE r4)."""
    trainer = _tiny_trainer()
    with pytest.raises(ValueError, match="re-iterable"):
        trainer.fit(_batches(3), max_steps=100, epochs=3)
    assert trainer.step == 0  # refused up front, nothing trained

    # A re-iterable loader that drains early terminates cleanly (no crash:
    # e.g. an elastic loader whose master-side epoch budget exhausted).
    class DrainOnce:
        def __init__(self):
            self.passes = 0

        def __iter__(self):
            self.passes += 1
            return iter(list(_batches(2)) if self.passes == 1 else [])

    final = trainer.fit(DrainOnce(), max_steps=100, epochs=3)
    assert final == 2  # trained what existed, counted epochs through


def test_resume_at_epoch_budget_runs_nothing(tmp_path):
    """A trainer resumed at/past its epoch budget must not run an extra
    epoch (the epoch check happens before each pass, ADVICE r4)."""
    data = list(_batches(3))
    trainer = _tiny_trainer(tmp_path=tmp_path)
    trainer.fit(data, max_steps=6, epochs=2)
    trainer.close()

    resumed = _tiny_trainer(tmp_path=tmp_path)
    assert resumed.step == 6
    resumed.fit(data, max_steps=100, epochs=2)  # budget already consumed
    assert resumed.step == 6  # zero additional steps
    resumed.close()


def test_nan_state_never_checkpointed(tmp_path):
    """Once the step scalars go non-finite the live state is poisoned;
    checkpoints taken after that would be restored by the master's
    restart remediation and loop the failure (ADVICE r4)."""
    import jax.numpy as jnp2

    trainer = _tiny_trainer(tmp_path=tmp_path)
    trainer.fit(list(_batches(2)), max_steps=2)
    good_step = trainer._last_saved
    assert good_step == 2

    # Poison via the save-time finiteness re-check (a NaN landing between
    # report ticks).
    trainer.step = 3
    trainer._last_metrics = {"loss": jnp2.float32(float("nan"))}
    trainer.save_checkpoint()
    assert trainer._last_saved == good_step  # skipped
    assert trainer._state_poisoned

    # The end-of-fit flush goes through the same gate.
    trainer.save_checkpoint()
    assert trainer._last_saved == good_step
    trainer.close()


def test_nan_report_poisons_state():
    """The monitor path: a NaN loss in _report marks the state poisoned."""
    trainer = _tiny_trainer()
    trainer.step = 5
    trainer._report({"loss": float("nan")})
    assert trainer._state_poisoned


def test_table_splitter_subepochs_bound_shard_count():
    """VERDICT r4 #7: huge datasets split into subepochs so the master
    never materializes more than max_shard_count shards at once (ref
    ``dataset_splitter.py:180-196``)."""
    params = DatasetShardParams(
        dataset_name="huge", dataset_size=1000, shard_size=10,
        num_epochs=2, max_shard_count=25,  # 100 shards/epoch > 25
    )
    splitter = TableDatasetSplitter(params)
    assert splitter._subepochs_per_epoch == 4
    all_ranges = []
    epochs_seen = []
    while not splitter.epoch_finished():
        shards = splitter.create_shards()
        assert len(shards) <= 25  # the OOM guard
        epochs_seen.append(shards[0].epoch)
        all_ranges.extend((s.start, s.end) for s in shards)
    # 2 user epochs x 4 subepochs each ran; every row covered twice.
    assert len(all_ranges) == 200
    covered = sorted(all_ranges)
    assert covered[0] == (0, 10) and covered[-1] == (990, 1000)
    assert epochs_seen == [0, 0, 0, 0, 1, 1, 1, 1]


def test_table_splitter_subepoch_shuffle_stays_in_window():
    params = DatasetShardParams(
        dataset_name="huge", dataset_size=100, shard_size=10,
        num_epochs=1, shuffle=True, max_shard_count=5,
    )
    splitter = TableDatasetSplitter(params)
    first = splitter.create_shards()
    # Shuffled ORDER, but every shard stays inside subepoch 0's window.
    assert all(s.end <= 50 for s in first)
    assert sorted(s.start for s in first) == [0, 10, 20, 30, 40]
    second = splitter.create_shards()
    assert all(s.start >= 50 for s in second)


def test_text_splitter_shuffle_yields_record_indices():
    """VERDICT r4 #7: shuffled text shards carry sample-level indices
    from a whole-epoch permutation (ref ``dataset_splitter.py:300-324``),
    not just a shuffled shard order."""
    params = DatasetShardParams(
        dataset_name="t", dataset_size=20, shard_size=8,
        num_epochs=2, shuffle=True, storage_type="text",
    )
    splitter = make_splitter(params)
    assert isinstance(splitter, TextDatasetSplitter)
    shards = splitter.create_shards()
    assert [len(s.record_indices) for s in shards] == [8, 8, 4]
    flat = [i for s in shards for i in s.record_indices]
    assert sorted(flat) == list(range(20))  # a permutation: every line once
    assert flat != list(range(20))  # and actually shuffled
    # Epoch 2 uses a different permutation.
    flat2 = [i for s in splitter.create_shards() for i in s.record_indices]
    assert sorted(flat2) == list(range(20)) and flat2 != flat


def test_text_unshuffled_stays_range_based():
    params = DatasetShardParams(
        dataset_name="t", dataset_size=20, shard_size=8,
        num_epochs=1, storage_type="text",
    )
    shards = make_splitter(params).create_shards()
    assert all(s.record_indices is None for s in shards)
    assert [(s.start, s.end) for s in shards] == [(0, 8), (8, 16), (16, 20)]


def test_record_indices_roundtrip_through_checkpoint():
    params = DatasetShardParams(
        dataset_name="t", dataset_size=12, shard_size=5,
        num_epochs=1, shuffle=True, storage_type="text",
    )
    manager = DatasetManager(make_splitter(params))
    task = manager.get_task(node_id=0)  # one in flight
    state = manager.checkpoint()

    fresh = DatasetManager(make_splitter(params))
    fresh.restore(state)
    restored = []
    while True:
        t = fresh.get_task(node_id=1)
        if t.empty:
            break
        restored.append(t)
        fresh.report_task(t.task_id, success=True)
    # Pending AND the in-flight shard both came back, indices intact.
    flat = sorted(i for t in restored for i in t.record_indices)
    assert flat == list(range(12))
    assert any(t.record_indices == task.record_indices for t in restored)


def test_text_reader_resolves_shuffled_indices(tmp_path):
    from dlrover_tpu.data.text_shards import TextShardReader

    path = tmp_path / "d.txt"
    path.write_text("".join(f"line {i}\n" for i in range(15)))
    reader = TextShardReader(str(path))
    params = DatasetShardParams(
        dataset_name="d", dataset_size=15, shard_size=6,
        num_epochs=1, shuffle=True, storage_type="text",
    )
    seen = []
    for shard in make_splitter(params).create_shards():
        lines = reader.read_task(shard)
        assert lines == [f"line {i}" for i in shard.record_indices]
        seen.extend(lines)
    assert sorted(seen) == sorted(f"line {i}" for i in range(15))
    reader.close()


def test_text_shuffle_bounded_by_subepoch_window():
    """The text splitter's permutation (and so shard-checkpoint size) is
    bounded by the max_shard_count window, like the table splitter."""
    params = DatasetShardParams(
        dataset_name="huge-text", dataset_size=100, shard_size=10,
        num_epochs=1, shuffle=True, storage_type="text",
        max_shard_count=5,  # 10 shards/epoch > 5 -> 2 subepochs
    )
    splitter = make_splitter(params)
    first = splitter.create_shards()
    assert len(first) == 5
    flat = [i for s in first for i in s.record_indices]
    assert sorted(flat) == list(range(50))  # only window 0's lines
    second = splitter.create_shards()
    flat2 = [i for s in second for i in s.record_indices]
    assert sorted(flat2) == list(range(50, 100))
    assert splitter.epoch_finished()
