"""Auto-scaler, metrics collector, resource monitor, hang remediation."""

import os
import sys
import time

import pytest

from dlrover_tpu.master.auto_scaler import JobAutoScaler
from dlrover_tpu.master.metrics import MetricsCollector
from dlrover_tpu.master.node_manager import (
    LocalNodeLauncher,
    NodeManager,
    NodeStatus,
)
from dlrover_tpu.master.speed_monitor import SpeedMonitor


class RecordingLauncher:
    def __init__(self):
        self.launched, self.deleted = [], []

    def launch(self, node_id):
        self.launched.append(node_id)

    def delete(self, node_id):
        self.deleted.append(node_id)


def _scaler(num_nodes=4, min_nodes=2, launcher=None):
    nm = NodeManager(num_nodes=num_nodes, launcher=launcher)
    scaler = JobAutoScaler(
        nm, SpeedMonitor(), min_nodes=min_nodes, max_nodes=num_nodes,
        cooldown_s=0.0,
    )
    return nm, scaler


def test_scaler_repairs_dead_node():
    launcher = RecordingLauncher()
    nm, scaler = _scaler(launcher=launcher)
    for n in range(4):
        nm.report_event(n, "started")
    assert scaler.step() is None  # steady state: no plan
    # Node 3 silently dies.
    nm._nodes[3].status = NodeStatus.DEAD
    plan = scaler.step()
    assert plan is not None and plan.launch == [3]
    assert launcher.launched == [3]
    assert nm.statuses()[3] == "pending"


def test_scaler_honors_target_and_node_unit():
    launcher = RecordingLauncher()
    nm = NodeManager(num_nodes=8, launcher=launcher)
    scaler = JobAutoScaler(
        nm, SpeedMonitor(), min_nodes=2, max_nodes=8, node_unit=2,
        cooldown_s=0.0,
    )
    for n in range(8):
        nm.report_event(n, "started")
    scaler.set_target(5)  # rounds down to 4 (node_unit=2)
    assert scaler.target == 4
    plan = scaler.step()
    assert sorted(plan.delete) == [4, 5, 6, 7]
    assert sorted(launcher.deleted) == [4, 5, 6, 7]
    # Scale back up to 6.
    scaler.set_target(6)
    plan = scaler.step()
    assert sorted(plan.launch) == [4, 5]


def test_scaler_respects_relaunch_budget():
    launcher = RecordingLauncher()
    nm = NodeManager(num_nodes=1, launcher=launcher, max_relaunches=1)
    scaler = JobAutoScaler(
        nm, SpeedMonitor(), min_nodes=1, max_nodes=1, cooldown_s=0.0
    )
    nm.report_event(0, "started")
    nm._nodes[0].status = NodeStatus.DEAD
    scaler.step()
    assert launcher.launched == [0]
    nm._nodes[0].status = NodeStatus.DEAD
    scaler.step()  # budget (1) exhausted: no second launch
    assert launcher.launched == [0]


def test_metrics_collector_series_and_staleness():
    mc = MetricsCollector()
    now = time.time()
    mc.collect(0, 50.0, 4.0, 2.0, 0.5, timestamp=now)
    mc.collect(1, 90.0, 8.0, timestamp=now - 1000)
    assert mc.latest(0)["cpu_percent"] == 50.0
    assert mc.nodes() == [0, 1]
    assert mc.stale_nodes(max_age_s=300) == [1]
    assert 0.0 < mc.mean_cpu() <= 100.0


def test_resource_monitor_samples_host_and_device_file(tmp_path):
    import json

    from dlrover_tpu.agent.monitor import ResourceMonitor

    class FakeClient:
        def __init__(self):
            self.reports = []

        def report_resource(self, *args):
            self.reports.append(args)

    metrics_file = str(tmp_path / "m.json")
    with open(metrics_file, "w") as f:
        json.dump({"device_mem_gb": 3.5, "device_util": 0.7}, f)
    mon = ResourceMonitor(FakeClient(), metrics_file=metrics_file)
    mon.sample()  # prime cpu delta
    time.sleep(0.05)
    s = mon.sample()
    assert s["mem_gb"] > 0
    assert s["device_mem_gb"] == 3.5
    assert s["device_util"] == 0.7


def test_write_device_metrics_roundtrip(tmp_path):
    from dlrover_tpu.agent.monitor import write_device_metrics

    path = str(tmp_path / "dev.json")
    payload = write_device_metrics(path)
    assert payload is not None and os.path.exists(path)
    import json

    on_disk = json.load(open(path))
    assert "device_mem_gb" in on_disk


def test_hang_remediation_breaks_world():
    from dlrover_tpu.master.job_master import JobMaster

    master = JobMaster(num_nodes=1, hang_threshold=0.1, auto_scale=False)
    try:
        rdzv = master.rdzv_managers["elastic-training"]
        rdzv.join_rendezvous(0, 1)
        rdzv.update_rdzv_params(1, 1, waiting_timeout=0.1)
        round_, _, world = rdzv.get_comm_world(0)
        assert world
        master.speed_monitor.collect_global_step(5, time.time() - 100)
        master._run_diagnosis()
        assert rdzv.world_changed(round_)
    finally:
        master.stop()


@pytest.mark.slow
def test_local_launcher_spawns_and_deletes_real_process(tmp_path):
    """The LocalNodeLauncher must actually spawn/kill host processes (the
    round-2 verdict: no real launcher impl existed)."""
    marker = str(tmp_path / "alive")
    launcher = LocalNodeLauncher(
        lambda nid: [
            sys.executable, "-c",
            f"import pathlib, time; "
            f"pathlib.Path({marker!r} + str({nid})).touch(); time.sleep(60)",
        ]
    )
    launcher.launch(2)
    deadline = time.monotonic() + 10
    while not os.path.exists(marker + "2"):
        assert time.monotonic() < deadline
        time.sleep(0.1)
    proc = launcher.procs[2]
    assert proc.poll() is None
    launcher.delete(2)
    assert proc.poll() is not None
