"""PPO RLHF trainer: rollout shapes, GAE math, reward improvement."""

import jax.numpy as jnp
import numpy as np
import pytest

from dlrover_tpu.models.gpt2 import gpt2_config
from dlrover_tpu.rl.ppo import PPOConfig, PPOTrainer, gae_advantages


def tiny_cfg():
    return gpt2_config(
        "124m", num_layers=1, d_model=32, num_heads=2,
        vocab_size=32, max_seq_len=24,
    )


def test_gae_matches_hand_computation():
    rewards = jnp.asarray([[0.0, 0.0, 1.0]])
    values = jnp.asarray([[0.1, 0.2, 0.3]])
    adv, ret = gae_advantages(rewards, values, gamma=1.0, lam=1.0)
    # With gamma=lam=1 and terminal bootstrap 0: adv_t = sum(r_t:) - v_t.
    np.testing.assert_allclose(adv[0], [0.9, 0.8, 0.7], atol=1e-6)
    np.testing.assert_allclose(ret[0], [1.0, 1.0, 1.0], atol=1e-6)


@pytest.mark.slow  # rollout generation compile, ~7s on 1 core
def test_rollout_fills_response_region():
    trainer = PPOTrainer(
        tiny_cfg(),
        reward_fn=lambda toks: np.zeros(toks.shape[0]),
        config=PPOConfig(rollout_len=6),
    )
    prompts = np.ones((3, 4), np.int32)
    roll = trainer.rollout(prompts)
    assert roll["tokens"].shape == (3, 10)
    np.testing.assert_array_equal(roll["tokens"][:, :4], 1)
    assert (roll["tokens"][:, 4:] < 32).all()


@pytest.mark.slow  # 12-step learning-curve e2e
def test_ppo_increases_task_reward():
    """Reward = frequency of token 7 in the response; PPO must learn to
    emit it (the classic token-bandit sanity check)."""
    target = 7

    def reward_fn(tokens):
        resp = tokens[:, 4:]
        return (resp == target).mean(axis=1).astype(np.float32) * 4.0

    trainer = PPOTrainer(
        tiny_cfg(),
        reward_fn,
        config=PPOConfig(
            rollout_len=8, kl_coef=0.01, learning_rate=3e-3,
            ppo_epochs=2, entropy_coef=0.0, temperature=1.0,
        ),
    )
    prompts = np.ones((16, 4), np.int32)
    rewards = [trainer.step(prompts)["mean_task_reward"] for _ in range(12)]
    early = np.mean(rewards[:3])
    late = np.mean(rewards[-3:])
    assert late > early + 0.3, f"no learning: {rewards}"


@pytest.mark.slow  # multi-step learning-curve e2e
def test_kl_penalty_tracks_divergence():
    trainer = PPOTrainer(
        tiny_cfg(),
        reward_fn=lambda toks: np.ones(toks.shape[0]),
        config=PPOConfig(rollout_len=4, kl_coef=0.5, learning_rate=5e-3),
    )
    prompts = np.ones((4, 4), np.int32)
    first = trainer.step(prompts)
    assert abs(first["mean_kl"]) < 1e-4  # actor == reference at start
    for _ in range(4):
        metrics = trainer.step(prompts)
    assert np.isfinite(metrics["loss"])


def test_sampler_rejects_bad_top_k():
    """top_k outside [0, vocab_size] is a config bug (negative indexes
    from the wrong end of the sort; > vocab silently truncates) — fail
    at construction, not deep inside a jitted sort."""
    from dlrover_tpu.rl.generation import GenerationBackend, SamplingParams

    cfg = tiny_cfg()
    with pytest.raises(ValueError, match="top_k must be >= 0"):
        GenerationBackend(cfg, SamplingParams(top_k=-1, max_new_tokens=4))
    with pytest.raises(ValueError, match="exceeds vocab_size"):
        GenerationBackend(
            cfg, SamplingParams(top_k=cfg.vocab_size + 1, max_new_tokens=4)
        )
    # top_k == vocab_size is just full categorical: allowed.
    GenerationBackend(
        cfg, SamplingParams(top_k=cfg.vocab_size, max_new_tokens=4)
    )


def test_zero_temperature_is_greedy_argmax():
    """temperature == 0 must mean greedy decoding, not division by the
    1e-6 clamp (which warps logits by 1e6 and can overflow to uniform
    garbage in float32)."""
    import jax

    from dlrover_tpu.rl.generation import GenerationBackend, SamplingParams

    backend = GenerationBackend(
        tiny_cfg(), SamplingParams(temperature=0.0, max_new_tokens=4)
    )
    logits = jnp.asarray(
        [[0.1, 3.0, -1.0, 2.9], [5.0, -5.0, 4.9, 0.0]], jnp.bfloat16
    )
    for seed in range(4):  # rng must not matter
        out = backend._sample(logits, jax.random.PRNGKey(seed))
        np.testing.assert_array_equal(np.asarray(out), [1, 0])


@pytest.mark.slow  # compiles a 2-stage pipeline build just to prove
# the raise, ~15s on 1 core
def test_kv_cache_requires_single_pipeline_stage():
    """use_kv_cache=True builds a decode backend whose params mirror a
    pipeline_stages=1 layer scan; a pipelined model config would feed it
    mismatched param trees — reject up front."""
    import dataclasses as dc

    cfg = dc.replace(tiny_cfg(), num_layers=2, pipeline_stages=2,
                     num_microbatches=1)
    with pytest.raises(ValueError, match="pipeline_stages == 1"):
        PPOTrainer(
            cfg,
            reward_fn=lambda toks: np.zeros(toks.shape[0]),
            config=PPOConfig(rollout_len=4, use_kv_cache=True),
        )
    # The full-reforward sampler path stays available for pipelined cfgs.
    PPOTrainer(
        cfg,
        reward_fn=lambda toks: np.zeros(toks.shape[0]),
        config=PPOConfig(rollout_len=4, use_kv_cache=False),
    )


def test_replay_buffer_sample_is_consistent_under_writers():
    """sample() snapshots the deque inside the lock — concurrent
    add_rollout must never make it stack ragged/partial rows."""
    import threading

    from dlrover_tpu.rl.replay_buffer import ReplayBuffer

    buf = ReplayBuffer(capacity=256, seed=0)
    buf.add_rollout({"x": np.arange(8, dtype=np.int64)})
    stop = threading.Event()
    errors = []

    def writer():
        i = 8
        while not stop.is_set():
            buf.add_rollout({"x": np.arange(i, i + 4, dtype=np.int64)})
            i += 4

    def reader():
        try:
            while not stop.is_set():
                batch = buf.sample(16)
                assert batch["x"].shape == (16,)
        except Exception as e:  # pragma: no cover - failure path
            errors.append(e)

    threads = [threading.Thread(target=writer),
               threading.Thread(target=reader)]
    for t in threads:
        t.start()
    import time
    time.sleep(0.5)
    stop.set()
    for t in threads:
        t.join(timeout=5.0)
    assert not errors, errors
    # Undersized buffers sample with replacement; rows stay intact.
    small = ReplayBuffer(capacity=8, seed=1)
    small.add_rollout({"x": np.asarray([3, 5], np.int64)})
    batch = small.sample(6)
    assert batch["x"].shape == (6,)
    assert set(batch["x"].tolist()) <= {3, 5}
