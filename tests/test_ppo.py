"""PPO RLHF trainer: rollout shapes, GAE math, reward improvement."""

import jax.numpy as jnp
import numpy as np
import pytest

from dlrover_tpu.models.gpt2 import gpt2_config
from dlrover_tpu.rl.ppo import PPOConfig, PPOTrainer, gae_advantages


def tiny_cfg():
    return gpt2_config(
        "124m", num_layers=1, d_model=32, num_heads=2,
        vocab_size=32, max_seq_len=24,
    )


def test_gae_matches_hand_computation():
    rewards = jnp.asarray([[0.0, 0.0, 1.0]])
    values = jnp.asarray([[0.1, 0.2, 0.3]])
    adv, ret = gae_advantages(rewards, values, gamma=1.0, lam=1.0)
    # With gamma=lam=1 and terminal bootstrap 0: adv_t = sum(r_t:) - v_t.
    np.testing.assert_allclose(adv[0], [0.9, 0.8, 0.7], atol=1e-6)
    np.testing.assert_allclose(ret[0], [1.0, 1.0, 1.0], atol=1e-6)


def test_rollout_fills_response_region():
    trainer = PPOTrainer(
        tiny_cfg(),
        reward_fn=lambda toks: np.zeros(toks.shape[0]),
        config=PPOConfig(rollout_len=6),
    )
    prompts = np.ones((3, 4), np.int32)
    roll = trainer.rollout(prompts)
    assert roll["tokens"].shape == (3, 10)
    np.testing.assert_array_equal(roll["tokens"][:, :4], 1)
    assert (roll["tokens"][:, 4:] < 32).all()


def test_ppo_increases_task_reward():
    """Reward = frequency of token 7 in the response; PPO must learn to
    emit it (the classic token-bandit sanity check)."""
    target = 7

    def reward_fn(tokens):
        resp = tokens[:, 4:]
        return (resp == target).mean(axis=1).astype(np.float32) * 4.0

    trainer = PPOTrainer(
        tiny_cfg(),
        reward_fn,
        config=PPOConfig(
            rollout_len=8, kl_coef=0.01, learning_rate=3e-3,
            ppo_epochs=2, entropy_coef=0.0, temperature=1.0,
        ),
    )
    prompts = np.ones((16, 4), np.int32)
    rewards = [trainer.step(prompts)["mean_task_reward"] for _ in range(12)]
    early = np.mean(rewards[:3])
    late = np.mean(rewards[-3:])
    assert late > early + 0.3, f"no learning: {rewards}"


def test_kl_penalty_tracks_divergence():
    trainer = PPOTrainer(
        tiny_cfg(),
        reward_fn=lambda toks: np.ones(toks.shape[0]),
        config=PPOConfig(rollout_len=4, kl_coef=0.5, learning_rate=5e-3),
    )
    prompts = np.ones((4, 4), np.int32)
    first = trainer.step(prompts)
    assert abs(first["mean_kl"]) < 1e-4  # actor == reference at start
    for _ in range(4):
        metrics = trainer.step(prompts)
    assert np.isfinite(metrics["loss"])
