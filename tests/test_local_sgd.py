"""Local SGD / HSDP outer loop and the GTA sign-consensus reducer."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from dlrover_tpu.parallel.local_sgd import (
    LocalSGD,
    LocalSGDConfig,
    gta_reduce,
)


@pytest.mark.slow  # multi-step consensus loop, ~1 min on the 1-core CI box
def test_gta_reduce_sign_consensus():
    deltas = [
        {"w": jnp.asarray([1.0, -1.0, 2.0])},
        {"w": jnp.asarray([3.0, -3.0, -2.0])},
        {"w": jnp.asarray([2.0, -2.0, 4.0])},
    ]
    out = gta_reduce(deltas)
    # Coords 0/1: full agreement -> plain mean.  Coord 2: majority positive,
    # the -2 dissenter is dropped -> mean(2, 4) = 3.
    np.testing.assert_allclose(out["w"], [2.0, -2.0, 3.0])


@pytest.mark.slow  # multi-step consensus loop, ~75s on the 1-core CI box
def test_gta_threshold_drops_weak_consensus():
    deltas = [
        {"w": jnp.asarray([1.0])},
        {"w": jnp.asarray([-1.0])},
        {"w": jnp.asarray([2.0])},
    ]
    # mean sign = 1/3; threshold 0.5 drops the coordinate entirely.
    out = gta_reduce(deltas, threshold=0.5)
    np.testing.assert_allclose(out["w"], [0.0])


@pytest.mark.slow  # 0.2s in isolation but measured a ~110s in-suite
# stall at this position on the CI box; the outer loop keeps three
# tier-1 witnesses below (momentum, threshold math, e2e train).
def test_outer_loop_syncs_on_schedule_with_momentum():
    fabric = {}

    def allgather(local):
        # Two simulated replicas: this one and a mirror-image peer.
        peer = jax.tree.map(lambda x: 2 * x, local)
        fabric["calls"] = fabric.get("calls", 0) + 1
        return [local, peer]

    cfg = LocalSGDConfig(sync_every=3, outer_lr=1.0, outer_momentum=0.0)
    outer = LocalSGD(cfg, allgather_fn=allgather)
    params = {"w": jnp.zeros((2,))}
    outer.init(params)
    for step in range(1, 7):
        # local training moves params by +1 each step
        params = jax.tree.map(lambda p: p + 1.0, params)
        params, synced = outer.maybe_sync(params)
        assert synced == (step % 3 == 0)
    # Round 1: local delta 3, peer 6 -> averaged 4.5. Round 2 same again.
    np.testing.assert_allclose(params["w"], [9.0, 9.0])
    assert fabric["calls"] == 2


def test_outer_momentum_accumulates():
    outer = LocalSGD(
        LocalSGDConfig(sync_every=1, outer_lr=1.0, outer_momentum=0.5),
        allgather_fn=lambda d: [d],
    )
    outer.init({"w": jnp.zeros(())})
    params = {"w": jnp.asarray(1.0)}
    params, synced = outer.maybe_sync(params)
    assert synced
    v1 = float(params["w"])
    params = jax.tree.map(lambda p: p + 1.0, params)
    params, _ = outer.maybe_sync(params)
    # velocity: d1 then 0.5*d1 + d2 -> second applied step exceeds delta.
    assert float(params["w"]) > v1 + 1.0


def test_local_sgd_trains_a_model_between_syncs():
    """End-to-end shape: independent local steps then an averaged outer
    step still reduces the loss."""

    def loss_fn(w, x, y):
        return jnp.mean((x @ w - y) ** 2)

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(32, 4)), jnp.float32)
    true_w = jnp.asarray(rng.normal(size=(4,)), jnp.float32)
    y = x @ true_w
    tx = optax.sgd(0.05)
    w = jnp.zeros((4,))
    opt_state = tx.init(w)
    outer = LocalSGD(
        LocalSGDConfig(sync_every=4, outer_momentum=0.9),
        allgather_fn=lambda d: [d, jax.tree.map(lambda t: 0.5 * t, d)],
    )
    outer.init(w)
    losses = []
    for _ in range(60):
        grads = jax.grad(loss_fn)(w, x, y)
        updates, opt_state = tx.update(grads, opt_state, w)
        w = optax.apply_updates(w, updates)
        w, _ = outer.maybe_sync(w)
        losses.append(float(loss_fn(w, x, y)))
    assert losses[-1] < losses[0] * 0.2
