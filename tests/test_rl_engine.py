"""RLHF engine parity (VERDICT r4 missing #4): KV-cache generation
backend, replay buffer, per-role meshes, PPO e2e with generation in the
loop on the virtual mesh.

Ref ``atorch/atorch/rl/model_engine/model_engine.py:1-496``,
``rl/inference_backend/``, ``rl/replay_buffer/``.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import trace_asserts

from dlrover_tpu.models.gpt2 import gpt2_config
from dlrover_tpu.models.transformer import TransformerLM
from dlrover_tpu.rl.engine import EnginePhase, RLHFEngine, RoleSpec
from dlrover_tpu.rl.generation import GenerationBackend, SamplingParams
from dlrover_tpu.rl.ppo import PPOConfig, PPOTrainer
from dlrover_tpu.rl.replay_buffer import ReplayBuffer
from dlrover_tpu.runtime.mesh import ParallelConfig

VOCAB, SEQ = 64, 32


def _cfg(**kw):
    return gpt2_config(
        "124m", num_layers=2, d_model=32, num_heads=2, vocab_size=VOCAB,
        max_seq_len=SEQ, param_dtype=jnp.float32, **kw
    )


# ---------------------------------------------------------------------------
# Generation backend: KV-cache decode == full-reforward logits
# ---------------------------------------------------------------------------


@pytest.mark.slow  # decode + full-forward compile pair, ~11s on 1 core
def test_kv_cache_decode_matches_full_forward():
    """The cached decode path must produce the same next-token logits as
    running the full sequence through the non-decode model."""
    cfg = _cfg()
    model = TransformerLM(cfg)
    rng = jax.random.PRNGKey(0)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 12), 0, VOCAB)
    params = model.init(rng, tokens)["params"]

    full_logits, _ = model.apply({"params": params}, tokens)

    dcfg = dataclasses.replace(cfg, decode=True)
    dmodel = TransformerLM(dcfg)
    # Prefill 8 tokens, then decode 4 one at a time.
    (pre_logits, _), state = dmodel.apply(
        {"params": params}, tokens[:, :8],
        positions=jnp.arange(8)[None, :], mutable=["cache"],
    )
    np.testing.assert_allclose(
        np.asarray(pre_logits), np.asarray(full_logits[:, :8]),
        rtol=2e-4, atol=2e-4,
    )
    cache = state["cache"]
    for i in range(8, 12):
        (step_logits, _), state = dmodel.apply(
            {"params": params, "cache": cache}, tokens[:, i:i + 1],
            positions=jnp.full((2, 1), i), mutable=["cache"],
        )
        cache = state["cache"]
        np.testing.assert_allclose(
            np.asarray(step_logits[:, 0]), np.asarray(full_logits[:, i]),
            rtol=2e-4, atol=2e-4,
        )


def test_generation_backend_jitted_loop():
    cfg = _cfg()
    model = TransformerLM(cfg)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, SEQ), jnp.int32)
    )["params"]
    backend = GenerationBackend(
        cfg, SamplingParams(max_new_tokens=6, temperature=1.0)
    )
    prompts = jax.random.randint(jax.random.PRNGKey(2), (3, 5), 0, VOCAB)
    tokens, logps = backend.generate(
        params, prompts, jax.random.PRNGKey(3)
    )
    assert tokens.shape == (3, 11)
    assert logps.shape == (3, 6)
    np.testing.assert_array_equal(
        np.asarray(tokens[:, :5]), np.asarray(prompts)
    )
    assert np.all(np.asarray(logps) <= 0)
    # Deterministic under the same key (one jitted program, no host RNG).
    tokens2, _ = backend.generate(params, prompts, jax.random.PRNGKey(3))
    np.testing.assert_array_equal(np.asarray(tokens), np.asarray(tokens2))


@pytest.mark.slow  # generate + reforward compiles two programs, ~12s on 1 core
def test_generation_backend_greedy_matches_reforward_argmax():
    """temperature->0 sampling through the cache must follow the argmax
    of the full-reforward logits (the two rollout paths agree)."""
    cfg = _cfg()
    model = TransformerLM(cfg)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, SEQ), jnp.int32)
    )["params"]
    backend = GenerationBackend(
        cfg, SamplingParams(max_new_tokens=5, temperature=1e-7)
    )
    prompts = jax.random.randint(jax.random.PRNGKey(2), (2, 4), 0, VOCAB)
    tokens, _ = backend.generate(params, prompts, jax.random.PRNGKey(3))
    # Re-derive greedily with the plain model.
    seq = np.asarray(prompts)
    for _ in range(5):
        logits, _ = model.apply({"params": params}, jnp.asarray(seq))
        nxt = np.asarray(jnp.argmax(logits[:, -1], axis=-1))
        seq = np.concatenate([seq, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(np.asarray(tokens), seq)


def test_sampler_top_k_matches_sort_reference():
    """The lax.top_k threshold must filter exactly like the old
    full-vocab-sort reference: same kth value, same surviving logits,
    so `categorical` under the same key draws the same token."""
    cfg = _cfg()
    k = 5
    backend = GenerationBackend(
        cfg, SamplingParams(max_new_tokens=2, temperature=0.7, top_k=k)
    )
    logits = jax.random.normal(jax.random.PRNGKey(4), (3, VOCAB))
    rng = jax.random.PRNGKey(9)
    got = backend._sample(logits, rng)

    scaled = logits.astype(jnp.float32) / 0.7
    kth = jnp.sort(scaled, axis=-1)[..., -k][..., None]
    ref_filtered = jnp.where(scaled >= kth, scaled, -1e15)
    ref = jax.random.categorical(rng, ref_filtered, axis=-1)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))
    # Every drawn token sits inside the row's true top-k set.
    topk_idx = np.asarray(jax.lax.top_k(scaled, k)[1])
    for row, tok in enumerate(np.asarray(got)):
        assert tok in topk_idx[row]


def test_prompt_buckets_share_one_trace():
    """Two distinct prompt widths inside one bucket must compile the
    generate program ONCE (the anti-recompile contract the serving
    bucketer gives rollouts) and pad causally inertly: on an exact-width
    prompt the bucketed backend matches the unbucketed one bitwise."""
    cfg = _cfg()
    model = TransformerLM(cfg)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, SEQ), jnp.int32)
    )["params"]
    sampling = SamplingParams(max_new_tokens=4, temperature=0.0)
    backend = GenerationBackend(cfg, sampling, prompt_buckets=(8, 16))
    rng = jax.random.PRNGKey(3)

    tokens5, _ = backend.generate(
        params, jax.random.randint(jax.random.PRNGKey(5), (2, 5), 1, VOCAB),
        rng,
    )
    with trace_asserts.assert_no_retrace("generate"):
        tokens7, _ = backend.generate(
            params,
            jax.random.randint(jax.random.PRNGKey(6), (2, 7), 1, VOCAB),
            rng,
        )
    # Both padded to the 8-wide bucket: same output width.
    assert tokens5.shape == (2, 12) and tokens7.shape == (2, 12)

    # Exact-width prompt: bucketed == unbucketed, bitwise.
    plain = GenerationBackend(cfg, sampling)
    prompts = jax.random.randint(jax.random.PRNGKey(7), (2, 8), 1, VOCAB)
    bucketed_tokens, bucketed_logps = backend.generate(params, prompts, rng)
    plain_tokens, plain_logps = plain.generate(params, prompts, rng)
    np.testing.assert_array_equal(
        np.asarray(bucketed_tokens), np.asarray(plain_tokens)
    )
    np.testing.assert_array_equal(
        np.asarray(bucketed_logps), np.asarray(plain_logps)
    )


# ---------------------------------------------------------------------------
# Replay buffer
# ---------------------------------------------------------------------------


def test_replay_buffer_rollout_rows_and_minibatches():
    buf = ReplayBuffer(capacity=16)
    buf.add_rollout({
        "tokens": np.arange(12).reshape(6, 2),
        "adv": np.arange(6.0),
    })
    assert len(buf) == 6
    batches = list(buf.minibatches(batch_size=2, epochs=2))
    assert len(batches) == 6  # 3 per epoch x 2 epochs
    for b in batches:
        assert b["tokens"].shape == (2, 2)
    # Every row appears exactly once per epoch.
    seen = sorted(
        int(b["adv"][i]) for b in batches[:3] for i in range(2)
    )
    assert seen == [0, 1, 2, 3, 4, 5]
    sample = buf.sample(4)
    assert sample["tokens"].shape == (4, 2)
    with pytest.raises(ValueError, match="ragged"):
        buf.add_rollout({"a": np.zeros((2,)), "b": np.zeros((3,))})


def test_replay_buffer_rejects_oversized_rollout():
    """A rollout larger than capacity must fail loudly — the FIFO would
    otherwise silently drop experience that is then never trained on."""
    buf = ReplayBuffer(capacity=4)
    with pytest.raises(ValueError, match="exceeds buffer capacity"):
        buf.add_rollout({"x": np.arange(6)})
    buf.add_rollout({"x": np.arange(4)})
    buf.add_rollout({"x": np.arange(2)})  # across rollouts FIFO still rolls
    assert len(buf) == 4


# ---------------------------------------------------------------------------
# Engine: per-role meshes + phases
# ---------------------------------------------------------------------------


def test_engine_places_roles_on_distinct_meshes():
    devices = jax.devices()[:4]
    cfg = _cfg()
    roles = {
        "actor": RoleSpec(
            parallel=ParallelConfig(data=2, tensor=2), trainable=True
        ),
        "ref": RoleSpec(parallel=ParallelConfig(data=4)),
        "critic": RoleSpec(
            parallel=ParallelConfig(data=4), trainable=True,
            kind="critic",
        ),
    }
    engine = RLHFEngine(cfg, roles=roles, devices=devices)
    assert dict(engine.mesh("actor").shape)["tensor"] == 2
    assert dict(engine.mesh("ref").shape)["data"] == 4

    model = TransformerLM(cfg)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, SEQ), jnp.int32)
    )["params"]
    placed = engine.place("actor", params)
    # Tensor-sharded role: some param has a tensor-split sharding.
    shardings = jax.tree.leaves(
        jax.tree.map(lambda a: a.sharding.spec, placed)
    )
    assert any("tensor" in str(s) for s in shardings)
    # The frozen ref gets the same values, placed per ITS mesh.
    ref = engine.sync_roles("actor", "ref")
    np.testing.assert_allclose(
        np.asarray(jax.tree.leaves(placed)[0]),
        np.asarray(jax.tree.leaves(ref)[0]),
    )
    engine.set_phase(EnginePhase.EXPERIENCE_GENERATION)
    assert engine.phase == EnginePhase.EXPERIENCE_GENERATION

    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, SEQ), 0, VOCAB)
    logp = engine.logprob_fn("actor")(placed, tokens)
    ref_logp = engine.logprob_fn("ref")(ref, tokens)
    # tp=2 vs dp reduce in different float32 orders: same values, looser
    # tolerance.
    np.testing.assert_allclose(
        np.asarray(logp), np.asarray(ref_logp), rtol=3e-3, atol=3e-3
    )


# ---------------------------------------------------------------------------
# PPO e2e: generation in the loop, engine placement, replay minibatches
# ---------------------------------------------------------------------------


@pytest.mark.slow  # full RLHF engine e2e
def test_ppo_e2e_with_engine_generation_and_replay():
    """The whole engine: KV-cache rollouts, per-role meshes (actor
    tensor-sharded, critic data-parallel), replay minibatching — reward
    for emitting token 7 must rise."""
    devices = jax.devices()[:4]
    cfg = _cfg()
    roles = {
        "actor": RoleSpec(
            parallel=ParallelConfig(data=2, tensor=2), trainable=True
        ),
        "ref": RoleSpec(parallel=ParallelConfig(data=4)),
        "critic": RoleSpec(
            parallel=ParallelConfig(data=4), trainable=True,
            kind="critic",
        ),
    }
    engine = RLHFEngine(cfg, roles=roles, devices=devices)

    def reward_fn(tokens):
        return (tokens[:, -8:] == 7).mean(axis=1).astype(np.float32)

    trainer = PPOTrainer(
        cfg, reward_fn,
        PPOConfig(
            rollout_len=8, learning_rate=5e-3, kl_coef=0.01,
            ppo_epochs=2, minibatch_size=4, use_kv_cache=True,
        ),
        engine=engine,
    )
    engine.set_phase(EnginePhase.EXPERIENCE_GENERATION)
    prompts = np.full((8, 4), 3, np.int32)
    first = trainer.step(prompts)
    engine.set_phase(EnginePhase.RL_TRAINING)
    rewards = [first["mean_task_reward"]]
    for _ in range(14):
        rewards.append(trainer.step(prompts)["mean_task_reward"])
    assert np.mean(rewards[-3:]) > np.mean(rewards[:3]) + 0.05, rewards


@pytest.mark.slow  # full RLHF engine e2e
def test_reward_model_role_replaces_reward_fn():
    """reward_fn=None: the engine's 'reward' role (a learned reward
    model) scores rollouts — the reference's reward-model key
    (``atorch/rl`` model_keys) rather than a hand-written fn."""
    from dlrover_tpu.rl.ppo import CriticModel

    devices = jax.devices()[:2]
    cfg = _cfg()
    roles = {
        "actor": RoleSpec(parallel=ParallelConfig(data=2), trainable=True),
        "ref": RoleSpec(parallel=ParallelConfig(data=2)),
        "critic": RoleSpec(parallel=ParallelConfig(data=2), trainable=True,
                           kind="critic"),
        "reward": RoleSpec(parallel=ParallelConfig(data=2), kind="critic"),
    }
    engine = RLHFEngine(cfg, roles=roles, devices=devices)
    rm_params = CriticModel(cfg).init(
        jax.random.PRNGKey(7), jnp.zeros((1, SEQ), jnp.int32)
    )["params"]
    engine.place("reward", rm_params)

    trainer = PPOTrainer(
        cfg, reward_fn=None,
        config=PPOConfig(rollout_len=4, ppo_epochs=1),
        engine=engine,
    )
    prompts = np.full((2, 4), 3, np.int32)
    metrics = trainer.step(prompts)
    assert np.isfinite(metrics["loss"])
    assert np.isfinite(metrics["mean_task_reward"])

    # Without an engine reward role, reward_fn=None must fail loudly.
    with pytest.raises(ValueError, match="reward"):
        PPOTrainer(cfg, reward_fn=None,
                   config=PPOConfig(rollout_len=4))
