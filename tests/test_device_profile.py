"""Measured device-time attribution, calibration loop, and the HTTP plane.

Covers the PR-14 observability stack end to end: the stdlib Chrome-trace
parser (``utils/device_profile``), the measured-row + calibration wire
emission, the master's :class:`CalibrationLedger` (EWMA math, servicer
routing, state-snapshot survival), ``auto/tune.apply_calibration``
re-ranking, the ``/metrics`` + ``/timeline`` + ``/healthz`` HTTP plane
(byte parity with the RPC render, seam-injected 503s), the
:class:`StepRegressionOperator` sentinel, and one real profiled CPU run
through :class:`ElasticTrainer` under the no-retrace contract.
"""

import gzip
import json
import os
import urllib.error
import urllib.request

import numpy as np
import pytest

import trace_asserts
from dlrover_tpu.common import faults, telemetry
from dlrover_tpu.master import messages as msg
from dlrover_tpu.master.calibration import CalibrationLedger
from dlrover_tpu.master.diagnosis import (
    ActionType,
    DiagnosisContext,
    StepRegressionOperator,
)
from dlrover_tpu.master.http_plane import MetricsHTTPServer
from dlrover_tpu.master.node_manager import NodeManager
from dlrover_tpu.master.servicer import MasterServicer
from dlrover_tpu.master.speed_monitor import SpeedMonitor
from dlrover_tpu.master.timeline import JobTimeline
from dlrover_tpu.utils import device_profile
from dlrover_tpu.utils.device_profile import (
    DeviceProfiler,
    DeviceWindow,
    emit_measured_phases,
    find_trace_file,
    modeled_kind_seconds,
    overlap_seconds,
    parse_device_trace,
)
from dlrover_tpu.utils.profiler import StepPipelineCounters


@pytest.fixture(autouse=True)
def _isolated(monkeypatch, tmp_path):
    """Unique job tag + socket dir per test (shared-shm hygiene), and no
    fault plan leaking across tests."""
    monkeypatch.setenv(
        "DLROVER_TPU_JOB", f"dp{os.getpid()}_{tmp_path.name}"
    )
    monkeypatch.setenv("DLROVER_TPU_SOCKET_DIR", str(tmp_path / "socks"))
    faults.reset()
    yield
    faults.reset()


# -- trace parsing ----------------------------------------------------------


def _meta(pid, name):
    return {"ph": "M", "name": "process_name", "pid": pid, "tid": 0,
            "args": {"name": name}}


def _op(pid, name, ts, dur):
    return {"ph": "X", "pid": pid, "tid": 1, "name": name,
            "ts": ts, "dur": dur}


def _write_trace(path, events, gz=False):
    body = json.dumps({"traceEvents": events})
    if gz:
        with gzip.open(path, "wt") as f:
            f.write(body)
    else:
        with open(path, "w") as f:
            f.write(body)
    return str(path)


def test_parse_prefers_real_device_plane_over_host(tmp_path):
    # A TPU pid exists, so the /host:CPU plane (including an HLO-shaped
    # name living there) must not count.
    events = [
        _meta(1, "/device:TPU:0"),
        _meta(2, "/host:CPU"),
        _op(1, "dot.1", 0, 100),
        _op(1, "fusion.2", 100, 100),
        _op(1, "all-reduce.3", 150, 100),
        _op(2, "dot.99", 0, 500),
        _op(2, "PjitFunction(f)", 0, 500),
    ]
    path = _write_trace(tmp_path / "t.trace.json", events)
    w = parse_device_trace(path)
    assert w is not None
    assert w.op_count == 3
    assert w.seconds("compute") == pytest.approx(200e-6)
    assert w.seconds("collective") == pytest.approx(100e-6)
    assert w.device_total_s == pytest.approx(300e-6)
    # all-reduce [150,250] overlaps fusion [100,200] for 50us of its 100us.
    assert w.overlap_fraction == pytest.approx(0.5)


def test_parse_cpu_fallback_filters_host_scaffolding(tmp_path):
    # No accelerator pid: fall back to the CPU plane, but only HLO-shaped
    # rows count — host scaffolding, our own dlrover.* annotations, jit_*
    # and anonymous while/digit envelopes are all rejected.
    events = [
        _meta(7, "/host:CPU"),
        _op(7, "PjitFunction(f)", 0, 999),
        _op(7, "$profiler.py:91 start_trace", 0, 999),
        _op(7, "TfrtCpuExecutable::Execute", 0, 999),
        _op(7, "dlrover.step", 0, 999),
        _op(7, "jit_train_step", 0, 999),
        _op(7, "while.3", 0, 999),
        _op(7, "42", 0, 999),
        _op(7, "dot.4", 0, 50),
        _op(7, "broadcast_add_fusion", 50, 25),
        _op(7, "reduce-window", 75, 25),
    ]
    path = _write_trace(tmp_path / "t.trace.json", events)
    w = parse_device_trace(path)
    assert w is not None
    assert w.op_count == 3
    assert w.phases == {"compute": pytest.approx(100e-6)}
    assert w.overlap_fraction == 0.0  # no collectives -> nothing exposed


def test_parse_malformed_traces_degrade_to_none(tmp_path):
    junk = tmp_path / "junk.trace.json"
    junk.write_text("this is not json{{{")
    assert parse_device_trace(str(junk)) is None
    # Valid JSON but zero device ops is equally a no-window.
    empty = _write_trace(
        tmp_path / "empty.trace.json", [_meta(1, "/host:CPU")]
    )
    assert parse_device_trace(empty) is None
    assert parse_device_trace(str(tmp_path / "missing.trace.json")) is None


def test_find_trace_file_descends_and_gzip_roundtrips(tmp_path):
    # The profiler nests its output under plugins/profile/<ts>/.
    nest = tmp_path / "plugins" / "profile" / "2026_08_05"
    nest.mkdir(parents=True)
    events = [_meta(1, "/device:TPU:0"), _op(1, "dot.1", 0, 10)]
    _write_trace(nest / "host.trace.json.gz", events, gz=True)
    found = find_trace_file(str(tmp_path))
    assert found and found.endswith(".trace.json.gz")
    w = parse_device_trace(found)
    assert w is not None and w.op_count == 1


def test_overlap_seconds_merges_before_intersecting():
    compute = [(0.0, 1.0), (0.5, 2.0)]  # merges to (0, 2)
    collective = [(1.5, 3.0)]
    assert overlap_seconds(compute, collective) == pytest.approx(0.5)
    assert overlap_seconds([(0.0, 1.0)], [(2.0, 3.0)]) == 0.0
    assert overlap_seconds([], [(0.0, 1.0)]) == 0.0


def test_modeled_kind_seconds_maps_phase_plan_rows():
    rows = [
        {"phase": "accumulate", "dur": 0.3},
        {"phase": "reduce", "dur": 0.1},
        {"phase": "update", "dur": 0.05},
        {"phase": "warp_drive", "dur": 9.0},  # unknown phase: ignored
    ]
    out = modeled_kind_seconds(rows)
    assert out == {
        "compute": pytest.approx(0.35), "collective": pytest.approx(0.1),
    }


# -- measured-row + calibration emission ------------------------------------


def _drain_enabled_recorder():
    rec = telemetry.recorder()
    was = rec.enabled
    rec.configure(enabled=True)
    rec.drain()
    return rec, was


def test_emit_measured_phases_books_rows_and_calibration():
    rec, was = _drain_enabled_recorder()
    try:
        window = DeviceWindow(
            phases={"compute": 0.2, "collective": 0.1},
            overlap_fraction=0.5, device_total_s=0.3, op_count=3,
        )
        rows = emit_measured_phases(
            window, step=50, t_span=10.0, wall_s=0.35,
            modeled_rows=[
                {"phase": "accumulate", "dur": 0.25},
                {"phase": "reduce", "dur": 0.05},
            ],
            cache_key="abc123",
        )
        events = rec.drain()
    finally:
        rec.configure(enabled=was)
    assert rows == 2
    measured = [
        e for e in events if e[4].get("source") == "measured"
    ]
    assert [e[0] for e in measured] == ["compute", "collective"]
    for name, kind, _t, dur, attrs in measured:
        assert kind == "span"
        assert attrs["src"] == "device"
        assert attrs["step"] == 50
        assert attrs["overlap"] == pytest.approx(0.5)
    # Sequential layout: collective starts where compute ends.
    assert measured[0][3] == pytest.approx(0.2)
    assert measured[1][3] == pytest.approx(0.1)
    calib = [e for e in events if e[0] == "calibration"]
    assert len(calib) == 1
    attrs = calib[0][4]
    assert attrs["cache_key"] == "abc123"
    assert attrs["measured_compute"] == pytest.approx(0.2)
    assert attrs["modeled_compute"] == pytest.approx(0.25)
    assert attrs["measured_collective"] == pytest.approx(0.1)
    assert attrs["modeled_collective"] == pytest.approx(0.05)
    assert attrs["wall_s"] == pytest.approx(0.35)
    # The device rows render on their own Perfetto thread per node.
    trace = telemetry.events_to_chrome_trace({0: events})
    threads = {
        e["args"]["name"] for e in trace["traceEvents"]
        if e.get("ph") == "M" and e.get("name") == "thread_name"
    }
    assert "device" in threads


def test_emit_measured_phases_noop_when_recorder_disabled():
    rec = telemetry.recorder()
    was = rec.enabled
    rec.configure(enabled=False)
    try:
        window = DeviceWindow(
            phases={"compute": 1.0}, overlap_fraction=0.0,
            device_total_s=1.0, op_count=1,
        )
        assert emit_measured_phases(
            window, step=1, t_span=0.0, wall_s=1.0, modeled_rows=[],
        ) == 0
    finally:
        rec.configure(enabled=was)


def test_profiler_cadence_and_disable_latch(monkeypatch, tmp_path):
    off = DeviceProfiler(0)
    assert not off.wants(100)
    assert not off.arm(100)

    prof = DeviceProfiler(10, trace_dir=str(tmp_path))
    assert prof.wants(10) and prof.wants(20) and not prof.wants(11)

    import jax

    def _boom(*a, **k):
        raise RuntimeError("no profiler backend")

    monkeypatch.setattr(jax.profiler, "start_trace", _boom)
    assert not prof.arm(10)
    # Latched: the cadence no longer even wants a window.
    assert prof._disabled and not prof.wants(20)
    assert prof.finish() is None  # no open window -> harmless


# -- calibration ledger ------------------------------------------------------


def test_calibration_ledger_ewma_and_aggregate():
    ledger = CalibrationLedger()
    ledger.observe("k1", "compute", 2.0, 1.0)
    assert ledger.ratios("k1")["compute"] == pytest.approx(2.0)  # seeds
    ledger.observe("k1", "compute", 1.0, 1.0)
    # 2.0 + 0.3 * (1.0 - 2.0)
    assert ledger.ratios("k1")["compute"] == pytest.approx(1.7)
    # Non-positive sides carry no signal.
    ledger.observe("k1", "collective", 0.0, 1.0)
    ledger.observe("k1", "collective", 1.0, 0.0)
    assert "collective" not in ledger.ratios("k1")
    # Empty key buckets under "uncacheable".
    ledger.observe("", "compute", 3.0, 1.0)
    assert ledger.ratios("uncacheable")["compute"] == pytest.approx(3.0)
    assert len(ledger) == 2
    # Aggregate = mean over keys: (1.7 + 3.0) / 2.
    assert ledger.ratios()["compute"] == pytest.approx(2.35)
    assert ledger.observations("k1")["compute"] == 2


def test_calibration_ledger_state_roundtrip():
    ledger = CalibrationLedger()
    ledger.observe("k", "compute", 1.5, 1.0)
    ledger.observe("k", "collective", 2.0, 1.0)
    snap = json.loads(json.dumps(ledger.state()))  # must be JSON-able
    fresh = CalibrationLedger()
    fresh.restore(snap)
    assert fresh.ratios("k") == pytest.approx(ledger.ratios("k"))
    assert fresh.observations("k") == ledger.observations("k")
    # Empty snapshot is a no-op, not a wipe.
    fresh.restore({})
    assert fresh.ratios("k")["compute"] == pytest.approx(1.5)


def test_apply_calibration_reranks_comm_heavy_candidate():
    from dlrover_tpu.auto import tune
    from dlrover_tpu.runtime.mesh import ParallelConfig

    ledger = CalibrationLedger()
    # Measurement says collectives run 2x slower than the model prices.
    ledger.observe("k", "collective", 2.0, 1.0)

    a = tune.Candidate(ParallelConfig(), "none")
    a.est_step_time, a.est_comm_time = 1.0, 0.6   # comm-heavy: wins on paper
    b = tune.Candidate(ParallelConfig(), "none")
    b.est_step_time, b.est_comm_time = 1.05, 0.05
    rej = tune.Candidate(ParallelConfig(), "none")
    rej.rejected = "oom"

    assert a.est_step_time < b.est_step_time  # pre-calibration ranking
    tune.apply_calibration([a, b, rej], ledger)
    # a: 0.4 * 1.0 + 0.6 * 2.0 = 1.6; b: 1.0 + 0.05 * 2.0 = 1.10
    assert a.est_step_time == pytest.approx(1.6)
    assert b.est_step_time == pytest.approx(1.10)
    assert b.est_step_time < a.est_step_time  # ranking flipped
    assert b.est_comm_time == pytest.approx(0.1)
    assert rej.est_step_time == pytest.approx(float("inf"))
    # None / empty ledgers are no-ops.
    tune.apply_calibration([b], None)
    tune.apply_calibration([b], CalibrationLedger())
    assert b.est_step_time == pytest.approx(1.10)


# -- servicer routing --------------------------------------------------------


def _calibration_event(cache_key="key1"):
    return (
        "calibration", "point", 123.0, 0.0,
        {
            "step": 50, "cache_key": cache_key, "overlap": 0.5,
            "wall_s": 0.4, "device_total_s": 0.3,
            "measured_compute": 0.2, "modeled_compute": 0.1,
            "measured_collective": 0.1, "modeled_collective": 0.1,
        },
    )


def test_servicer_routes_calibration_events_and_dropped():
    timeline = JobTimeline()
    ledger = CalibrationLedger()
    servicer = MasterServicer(timeline=timeline, calibration=ledger)
    env = msg.Envelope(
        node_id=0, node_type="worker", job_name="local",
        payload=msg.TelemetryEvents(
            node_id=0, events=(_calibration_event(),), dropped=3,
        ),
    )
    servicer._report_telemetry(env)
    assert ledger.ratios("key1")["compute"] == pytest.approx(2.0)
    assert ledger.ratios("key1")["collective"] == pytest.approx(1.0)
    assert timeline.counter("telemetry_dropped") == 3
    text = servicer._get_metrics_text(None)
    assert 'dlrover_calibration_ratio{phase="compute"} 2' in text
    assert "dlrover_telemetry_dropped_total 3" in text
    assert "dlrover_perf_regressions_total 0" in text


def test_pipeline_counters_track_dropped_events():
    counters = StepPipelineCounters()
    counters.record_dropped(3)
    counters.record_dropped(0)   # no-op
    counters.record_dropped(-5)  # no-op
    assert counters.summary()["dropped_events"] == 3
    counters.reset()
    assert counters.summary()["dropped_events"] == 0


# -- HTTP plane --------------------------------------------------------------


def _http_get(port, path, timeout=5.0):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=timeout
    ) as resp:
        return resp.status, resp.read()


def test_http_plane_serves_metrics_timeline_healthz():
    timeline = JobTimeline()
    timeline.record(0, "step", kind="span", duration_s=0.1,
                    attrs={"step": 1})
    nodes = NodeManager(num_nodes=2)
    ledger = CalibrationLedger()
    ledger.observe("k", "compute", 1.5, 1.0)
    servicer = MasterServicer(
        timeline=timeline, node_manager=nodes,
        speed_monitor=SpeedMonitor(), calibration=ledger,
    )
    plane = MetricsHTTPServer(servicer, host="127.0.0.1", port=0)
    port = plane.start()
    try:
        # Byte parity with the RPC render path.
        status, body = _http_get(port, "/metrics")
        assert status == 200
        assert body == servicer._get_metrics_text(None).encode()
        assert b"dlrover_calibration_ratio" in body

        status, body = _http_get(port, "/timeline")
        trace = json.loads(body)
        assert any(
            e.get("name") == "step" for e in trace["traceEvents"]
        )

        status, body = _http_get(port, "/healthz")
        health = json.loads(body)
        assert health["ok"] is True and health["quarantined"] == []

        # Quarantine flips /healthz without touching anything else.
        nodes.quarantine(1, "sdc")
        health = json.loads(_http_get(port, "/healthz")[1])
        assert health["ok"] is False and health["quarantined"] == [1]

        with pytest.raises(urllib.error.HTTPError) as err:
            _http_get(port, "/teapot")
        assert err.value.code == 404
    finally:
        plane.stop()


def test_http_plane_seam_answers_503():
    servicer = MasterServicer(timeline=JobTimeline())
    plane = MetricsHTTPServer(servicer, host="127.0.0.1", port=0)
    port = plane.start()
    try:
        faults.configure("http.serve:error")
        with pytest.raises(urllib.error.HTTPError) as err:
            _http_get(port, "/metrics")
        assert err.value.code == 503
        faults.reset()
        status, _ = _http_get(port, "/metrics")
        assert status == 200
    finally:
        faults.reset()
        plane.stop()


def test_calibration_survives_master_state_snapshot(tmp_path):
    from dlrover_tpu.master.job_master import JobMaster

    state = str(tmp_path / "master_state.json")
    master = JobMaster(num_nodes=2, auto_scale=False, state_path=state)
    master.calibration.observe("k", "collective", 2.0, 1.0)
    master.calibration.observe("k", "compute", 1.2, 1.0)
    master._state_store.save(master)

    reborn = JobMaster(num_nodes=2, auto_scale=False, state_path=state)
    assert reborn._state_store.restore(reborn)
    assert reborn.calibration.ratios("k")["collective"] == pytest.approx(2.0)
    assert reborn.calibration.ratios("k")["compute"] == pytest.approx(1.2)
    text = reborn.servicer._get_metrics_text(None)
    assert 'dlrover_calibration_ratio{phase="collective"} 2' in text


# -- regression sentinel -----------------------------------------------------


class _FakeSpeedMonitor:
    def __init__(self):
        self.compiles = 0
        self.resizes = 0

    def compile_ledger(self):
        return {"compile_events": self.compiles}

    def resize_ledger(self):
        return {"resizes": self.resizes}


def test_step_regression_operator_fires_latches_and_resets():
    timeline = JobTimeline()
    sm = _FakeSpeedMonitor()
    op = StepRegressionOperator()
    ctx = DiagnosisContext(
        speed_monitor=sm, metrics=None, node_manager=None,
        timeline=timeline,
    )

    def steps(start, n, dur):
        for i in range(start, start + n):
            timeline.record(0, "step", kind="span", duration_s=dur,
                            attrs={"step": i})

    steps(1, 8, 0.1)
    assert op.observe(ctx) == []  # baseline frozen at 0.1
    steps(9, 8, 0.1)
    assert op.observe(ctx) == []  # steady state: no drift
    steps(17, 8, 0.2)
    actions = op.observe(ctx)
    assert len(actions) == 1
    assert actions[0].action == ActionType.REPORT
    assert "regressed" in actions[0].reason
    assert timeline.counter("perf_regressions") == 1
    # Latched: one report per generation, not one per tick.
    assert op.observe(ctx) == []
    assert timeline.counter("perf_regressions") == 1
    # A resize starts a new generation: relearn instead of alarming.
    sm.resizes = 1
    assert op.observe(ctx) == []
    assert op._baseline == pytest.approx(0.2)
    assert not op._fired


def test_step_regression_waits_for_a_full_window():
    timeline = JobTimeline()
    op = StepRegressionOperator()
    ctx = DiagnosisContext(
        speed_monitor=_FakeSpeedMonitor(), metrics=None,
        node_manager=None, timeline=timeline,
    )
    for i in range(1, 9):
        timeline.record(0, "step", kind="span", duration_s=0.1,
                        attrs={"step": i})
    assert op.observe(ctx) == []
    # Only 4 slow steps after baseline: window too short, stay silent.
    for i in range(9, 13):
        timeline.record(0, "step", kind="span", duration_s=0.5,
                        attrs={"step": i})
    assert op.observe(ctx) == []
    assert timeline.counter("perf_regressions") == 0


def test_regression_operator_without_timeline_is_silent():
    ctx = DiagnosisContext(
        speed_monitor=_FakeSpeedMonitor(), metrics=None,
        node_manager=None, timeline=None,
    )
    assert StepRegressionOperator().observe(ctx) == []


# -- tracelint: the HTTP plane's socket I/O is seam-covered -----------------


HTTP_PLANE_FIXTURE = """\
import socket
from dlrover_tpu.common import faults

def serve(port):
    faults.fire("http.serve", op="bind", port=port)
    return socket.create_connection(("127.0.0.1", port))
"""


def test_seam001_recognizes_http_serve_seam(tmp_path):
    from dlrover_tpu.analysis import run_paths
    from dlrover_tpu.analysis.rules.seams import known_seams

    assert "http.serve" in known_seams()
    (tmp_path / "master").mkdir()
    path = tmp_path / "master" / "plane.py"
    path.write_text(HTTP_PLANE_FIXTURE)
    report = run_paths(
        [str(path)], select=["SEAM001"], root=str(tmp_path)
    )
    assert report.findings == []
    # The same socket call WITHOUT the seam is a drillability gap.
    path.write_text(
        HTTP_PLANE_FIXTURE.replace(
            '    faults.fire("http.serve", op="bind", port=port)\n', ""
        )
    )
    report = run_paths(
        [str(path)], select=["SEAM001"], root=str(tmp_path)
    )
    assert [f.rule for f in report.findings] == ["SEAM001"]


# -- profiled CPU run through the trainer ------------------------------------


def _loader(batches, batch, seq, vocab=256, seed=0):
    rng = np.random.default_rng(seed)
    for _ in range(batches):
        toks = rng.integers(0, vocab, size=(batch, seq + 1), dtype=np.int32)
        yield {"inputs": toks[:, :-1], "targets": toks[:, 1:]}


@pytest.mark.slow  # real profiled train run + trace parse, ~12s; the
# parse/emit contract keeps its tier-1 witnesses on fixture traces.
def test_profiled_cpu_run_books_measured_rows(tmp_path):
    from dlrover_tpu.models.gpt2 import gpt2_config
    from dlrover_tpu.trainer.elastic_trainer import (
        ElasticTrainer,
        TrainerConfig,
    )

    model = gpt2_config(
        "124m", num_layers=1, d_model=64, num_heads=2,
        vocab_size=256, max_seq_len=32,
    )
    trainer = ElasticTrainer(
        model,
        TrainerConfig(
            global_batch_size=8, seq_len=32, learning_rate=1e-2,
            checkpoint_dir=str(tmp_path / "ckpt"),
            profile_every=2,
        ),
        client=None,
    )
    assert trainer._device_profiler is not None
    rec, was = _drain_enabled_recorder()
    try:
        loader = _loader(4, 8, 32)
        trainer.train_step(next(loader))  # step 1: pays the compile
        # Step 2 is a capture window; the TraceAnnotation + profiler
        # window must not retrace the compiled step program.
        with trace_asserts.assert_no_retrace("train_step"):
            trainer.train_step(next(loader))
            trainer.train_step(next(loader))
        events = rec.drain()
    finally:
        rec.configure(enabled=was)
        trainer.close()
    assert trainer._device_profiler.windows >= 1
    measured = [
        e for e in events if e[4].get("source") == "measured"
    ]
    assert measured, "a captured step must book measured phase rows"
    assert all(e[4].get("src") == "device" for e in measured)
    assert any(e[4].get("step") == 2 for e in measured)
    calib = [e for e in events if e[0] == "calibration"]
    assert calib
    attrs = calib[0][4]
    assert attrs["measured_compute"] > 0.0
    assert attrs["modeled_compute"] > 0.0
    assert attrs["cache_key"]  # cacheable tiny model -> a real key
    # The measured rows render on a distinct device track per node.
    trace = telemetry.events_to_chrome_trace({0: events})
    threads = {
        e["args"]["name"] for e in trace["traceEvents"]
        if e.get("ph") == "M" and e.get("name") == "thread_name"
    }
    assert "device" in threads


def test_profile_every_zero_constructs_nothing(tmp_path):
    # The default path must not even import device_profile: the knob off
    # means zero new objects on the step path.
    from dlrover_tpu.trainer.elastic_trainer import TrainerConfig

    assert TrainerConfig(
        global_batch_size=8, seq_len=32
    ).profile_every == 0
    prof = DeviceProfiler(0)
    assert not prof.wants(1) and not prof.arm(1)
