"""Ulysses SP lowering: the seq<->heads switch must compile to a clean ICI
all-to-all, never a replicate-then-repartition of full activations (the
round-2 verdict's "involuntary full rematerialization" finding)."""

import re

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlrover_tpu.models.gpt2 import gpt2_config
from dlrover_tpu.models.transformer import TransformerLM
from dlrover_tpu.parallel import rules as lr
from dlrover_tpu.runtime.mesh import ParallelConfig, build_mesh
from dlrover_tpu.trainer import train_lib

BATCH, SEQ = 8, 32


def _compiled_step_text(parallel: ParallelConfig) -> str:
    config = gpt2_config(
        "124m", num_layers=2, d_model=64, num_heads=4,
        vocab_size=512, max_seq_len=SEQ,
    )
    model = TransformerLM(config)
    mesh = build_mesh(parallel)
    opt = train_lib.make_optimizer("adamw", learning_rate=1e-3)
    train = train_lib.build_sharded_train(
        model, opt, mesh, lr.DEFAULT_RULES,
        global_batch_size=BATCH, seq_len=SEQ,
    )
    state_shape = jax.eval_shape(train.init_fn, jax.random.PRNGKey(0))
    batch_shape = {
        k: jax.ShapeDtypeStruct(
            (BATCH, SEQ), jnp.float32 if k == "weights" else jnp.int32
        )
        for k in ("inputs", "targets", "weights")
    }
    with train_lib.use_mesh(mesh):
        return train.step_fn.lower(state_shape, batch_shape).compile().as_text()


@pytest.mark.slow
def test_sp_step_lowers_to_all_to_all_without_full_gather():
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual mesh")
    txt = _compiled_step_text(ParallelConfig(data=2, seq=2, tensor=2))
    assert "all-to-all" in txt, "Ulysses boundary did not lower to a2a"
    # The failure mode being guarded: replicating the full [B,S,H,D]
    # activation (all-gather to unsharded) at the attention boundary.
    full_qkv = rf"all-gather[^=]*=\s*bf16\[{BATCH},{SEQ},4,16\]"
    assert not re.search(full_qkv, txt), (
        "attention boundary all-gathers the full activation (involuntary "
        "rematerialization)"
    )


@pytest.mark.slow
def test_sp_matches_dp_numerically():
    """The explicit a2a path must compute the same step as plain DP."""
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual mesh")
    config = gpt2_config(
        "124m", num_layers=2, d_model=64, num_heads=4,
        vocab_size=512, max_seq_len=SEQ,
    )
    model = TransformerLM(config)
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, 512, size=(BATCH, SEQ + 1), dtype=np.int32)
    losses = {}
    for name, parallel in {
        "dp": ParallelConfig(data=-1),
        "sp_tp": ParallelConfig(data=2, seq=2, tensor=2),
    }.items():
        mesh = build_mesh(parallel)
        opt = train_lib.make_optimizer("adamw", learning_rate=1e-3)
        train = train_lib.build_sharded_train(
            model, opt, mesh, lr.DEFAULT_RULES,
            global_batch_size=BATCH, seq_len=SEQ,
        )
        state = train.init(jax.random.PRNGKey(0))
        batch = train_lib.shard_batch(
            {"inputs": tokens[:, :-1], "targets": tokens[:, 1:]}, train
        )
        for _ in range(2):
            state, metrics = train.step(state, batch)
        losses[name] = float(metrics["loss"])
    np.testing.assert_allclose(losses["dp"], losses["sp_tp"], rtol=2e-2)
