"""Async step pipeline (ISSUE 2): device prefetch, deferred metrics,
restart-fast compile.

Covers the three pipeline contracts on the virtual CPU mesh:

* **Overlap/order** — the DevicePrefetcher issues batch N+1's placement
  before batch N is handed out (and before step N's metrics are fetched),
  preserves order, and keeps the loader's ack-after-consume semantics.
* **Sync budget** — a pipelined fit performs ZERO per-step synchronous
  metric fetches (<= 1 blocking sync per ``metrics_lag`` steps, all of
  them "metrics-flush" blocks), with exact numeric parity and correct
  step attribution vs the synchronous loop.
* **Restart-fast compile** — a second trainer with identical
  (config, mesh-shape) reuses the compiled program with zero retraces,
  and the compile event lands in the master's goodput ledger with restart
  time booked separately.
"""

import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dlrover_tpu.data.loader import DevicePrefetcher, ElasticDataLoader
from dlrover_tpu.models.gpt2 import gpt2_config
from dlrover_tpu.trainer import train_lib
from dlrover_tpu.trainer.elastic_trainer import (
    ElasticTrainer,
    TrainerConfig,
)
from dlrover_tpu.utils.profiler import pipeline_counters

import trace_asserts

BATCH, SEQ = 8, 32


@pytest.fixture(autouse=True)
def _isolated_shm(monkeypatch, tmp_path):
    monkeypatch.setenv("DLROVER_TPU_JOB", f"sp{os.getpid()}_{tmp_path.name}")
    monkeypatch.setenv("DLROVER_TPU_SOCKET_DIR", str(tmp_path / "socks"))


def _tiny_trainer(vocab=128, **cfg_kwargs):
    model_config = gpt2_config(
        "124m", num_layers=2, d_model=64, num_heads=2, vocab_size=vocab,
        max_seq_len=SEQ, param_dtype=jnp.float32,
    )
    cfg_kwargs.setdefault("report_every", 2)
    cfg = TrainerConfig(
        global_batch_size=BATCH, seq_len=SEQ, learning_rate=1e-2,
        ckpt_every=1000, **cfg_kwargs,
    )
    return ElasticTrainer(model_config, cfg, client=None)


def _batches(n, vocab=128, seed=0):
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        toks = rng.integers(0, vocab, size=(BATCH, SEQ + 1), dtype=np.int32)
        out.append({
            "inputs": toks[:, :-1].copy(), "targets": toks[:, 1:].copy(),
        })
    return out


# ---------------------------------------------------------------------------
# DevicePrefetcher
# ---------------------------------------------------------------------------


def test_prefetcher_preserves_order_and_places_ahead():
    n = 6
    placed = []

    def place(batch):
        placed.append(batch["i"])
        return batch

    pf = DevicePrefetcher([{"i": i} for i in range(n)], place, depth=2)
    placed_at_yield = []
    got = []
    for batch in pf:
        placed_at_yield.append(len(placed))
        got.append(batch["i"])
    assert got == list(range(n))            # order preserved
    assert placed == list(range(n))         # each batch placed exactly once
    for k, n_placed in enumerate(placed_at_yield):
        # When batch k is handed out, batch k+1 (at least) has already
        # been placed — the H2D-overlaps-compute contract.
        assert n_placed >= min(k + 2, n)


def test_prefetcher_reiterable_and_clean_shutdown():
    src = [{"i": i} for i in range(5)]
    pf = DevicePrefetcher(src, lambda b: b, depth=3)
    first = []
    for batch in pf:
        first.append(batch["i"])
        if batch["i"] == 1:
            break                            # abandon mid-pipeline
    assert first == [0, 1]
    assert [b["i"] for b in pf] == [0, 1, 2, 3, 4]  # fresh full pass


class _FakeTaskMaster:
    def __init__(self, num_shards, shard_size):
        self.tasks = [
            type("T", (), dict(
                task_id=i, start=i * shard_size, end=(i + 1) * shard_size,
                empty=False, epoch=0, dataset_name="d",
            ))()
            for i in range(num_shards)
        ]
        self.done = []

    def create_dataset(self, params):
        pass

    def get_task(self, name):
        if self.tasks:
            return self.tasks.pop(0)
        return type("T", (), dict(task_id=-1, empty=True))()

    def report_task(self, name, task_id, success):
        self.done.append(task_id)


def test_prefetcher_ack_only_after_consume():
    """Device-buffering a batch must NOT ack its shards — only the
    consumer coming back for the next batch proves batch N was trained."""
    from dlrover_tpu.data.sharding_client import ShardingClient

    fake = _FakeTaskMaster(num_shards=4, shard_size=8)
    loader = ElasticDataLoader(
        lambda i: {"x": np.asarray([i])}, batch_size=8,
        source=ShardingClient(fake, "d", create=False), prefetch=2,
    )
    pf = DevicePrefetcher(loader, lambda b: b, depth=2)
    it = iter(pf)
    next(it)   # batch 0 handed out (batches 1-2 already device-buffered)
    assert fake.done == []
    next(it)   # consumer came back: batch 0 consumed -> shard 0 acks
    assert fake.done == [0]
    it.close()  # abandon: buffered-but-unconsumed shards stay unacked
    assert fake.done == [0]

    fake2 = _FakeTaskMaster(num_shards=3, shard_size=8)
    loader2 = ElasticDataLoader(
        lambda i: {"x": np.asarray([i])}, batch_size=8,
        source=ShardingClient(fake2, "d", create=False), prefetch=2,
    )
    assert len(list(DevicePrefetcher(loader2, lambda b: b, depth=2))) == 3
    assert sorted(fake2.done) == [0, 1, 2]


def test_threaded_loader_generation_token_reiteration():
    """Abandoning a threaded iteration mid-pass must not let its producer
    leak items into (or consume source for) the next iteration."""
    loader = ElasticDataLoader(
        lambda i: {"x": np.asarray([i])}, batch_size=4,
        source=list(range(16)), prefetch=2,
    )
    it = iter(loader)
    first = next(it)
    assert list(first["x"].reshape(-1)) == [0, 1, 2, 3]
    it.close()  # producer of generation 1 must stand down
    gen_after_first = loader._generation
    assert gen_after_first == 1
    batches = list(loader)  # generation 2: a clean, complete pass
    assert loader._generation == 2
    flat = [int(v) for b in batches for v in b["x"].reshape(-1)]
    assert flat == list(range(16))


# ---------------------------------------------------------------------------
# Deferred metrics: sync budget, ordering, parity
# ---------------------------------------------------------------------------


def test_pipelined_fit_sync_budget_and_place_order():
    trainer = _tiny_trainer(
        metrics_lag=3, prefetch_to_device=2, report_every=1,
    )
    counters = pipeline_counters()
    counters.reset()
    trainer.fit(_batches(6), max_steps=6)
    summary = counters.summary()
    # ZERO per-step synchronous fetches; <= 1 blocking sync per lag steps.
    assert summary["sync_block_count"] == 0
    assert summary["flush_block_count"] == 2      # 6 steps / lag 3
    assert summary["host_block_count"] <= 6 // 3
    assert summary["dispatch_count"] == 6
    assert summary["place_count"] == 6
    # Order: batch N+1's device_put was issued before step N's metrics
    # were fetched.  The first block covers steps 1..3, so placements for
    # batches 1..4 (at least) must precede it in the event log.
    events = counters.events
    first_block = next(
        i for i, e in enumerate(events) if e.kind == "block"
    )
    covered = max(events[first_block].steps)
    places_before = sum(
        1 for e in events[:first_block] if e.kind == "place"
    )
    assert places_before >= covered + 1


def test_lagged_parity_with_sync_loop():
    """Same seed, same batches: the pipelined loop must report the exact
    losses of the synchronous loop, attributed to the exact same steps."""
    batches = _batches(6, seed=3)

    def run(**cfg):
        trainer = _tiny_trainer(**cfg)
        seen = []

        def on_step(step, metrics):
            seen.append((step, float(metrics["loss"])))

        trainer.fit(batches, max_steps=6, on_step=on_step)
        params = jax.device_get(
            jax.tree_util.tree_leaves(trainer.state.params)
        )
        return seen, params

    sync_seen, sync_params = run(metrics_lag=0, prefetch_to_device=0)
    lag_seen, lag_params = run(metrics_lag=4, prefetch_to_device=2)
    assert [s for s, _ in sync_seen] == [s for s, _ in lag_seen]
    for (s0, l0), (s1, l1) in zip(sync_seen, lag_seen):
        assert s0 == s1
        np.testing.assert_allclose(l0, l1, rtol=0, atol=0)
    for a, b in zip(sync_params, lag_params):
        np.testing.assert_array_equal(a, b)


def test_flush_on_eval_and_final_step_drains_ring():
    events = []

    class Rec:
        def on_train_begin(self, t):
            pass

        def on_step_end(self, t, step, metrics):
            events.append(("step", step, float(metrics["loss"])))

        def on_evaluate(self, t, step, m):
            events.append(("eval", step))

        def on_checkpoint(self, t, step):
            pass

        def on_epoch_end(self, t, epoch):
            pass

        def on_train_end(self, t, step):
            events.append(("end", step))

    trainer = _tiny_trainer(
        metrics_lag=10, prefetch_to_device=1, report_every=1,
        eval_every=3, eval_batches=2,
    )
    trainer.callbacks.append(Rec())
    trainer.fit(
        _batches(5), max_steps=5, eval_loader=_batches(2, seed=9),
    )
    # The eval at step 3 forces a flush: steps 1..3 must be delivered (in
    # order) before the eval event, despite lag 10 > 5 total steps.
    kinds = [e[0] for e in events]
    eval_at = kinds.index("eval")
    assert [e[1] for e in events[:eval_at] if e[0] == "step"] == [1, 2, 3]
    # End-of-fit barrier drains the rest before on_train_end.
    step_events = [e for e in events if e[0] == "step"]
    assert [e[1] for e in step_events] == [1, 2, 3, 4, 5]
    assert all(np.isfinite(e[2]) for e in step_events)
    assert kinds[-1] == "end"


def test_eval_accumulates_on_device_single_fetch():
    trainer = _tiny_trainer()
    counters = pipeline_counters()
    counters.reset()
    out = trainer.evaluate(_batches(3, seed=5), max_batches=3)
    assert out["eval_batches"] == 3
    assert np.isfinite(out["eval_loss"])
    assert out["eval_tokens"] > 0
    # One blocking fetch for the whole eval pass, no per-batch syncs.
    assert len(counters.blocks("eval-fetch")) == 1
    assert counters.sync_block_count() == 0


# ---------------------------------------------------------------------------
# Restart-fast compile
# ---------------------------------------------------------------------------


def test_second_trainer_zero_retraces():
    train_lib.reset_build_cache()
    t1 = _tiny_trainer(vocab=96)
    t1.fit(_batches(2, vocab=96), max_steps=2)
    assert train_lib.trace_count("train_step") >= 1
    with trace_asserts.assert_no_retrace("train_step", "init"):
        t2 = _tiny_trainer(vocab=96)   # identical (config, mesh-shape)
        assert t2.train is t1.train    # in-process program reuse
        t2.fit(_batches(2, vocab=96), max_steps=2)  # ZERO retraces


class _FakeClient:
    def __init__(self):
        self.events = []
        self.steps = []

    def report_event(self, event, detail=""):
        self.events.append((event, detail))

    def report_step(self, step, tokens=0, loss=0.0, anomalies=()):
        self.steps.append(step)


def _warmup_trainer(client):
    return ElasticTrainer(
        gpt2_config(
            "124m", num_layers=2, d_model=64, num_heads=2, vocab_size=80,
            max_seq_len=SEQ, param_dtype=jnp.float32,
        ),
        TrainerConfig(
            global_batch_size=BATCH, seq_len=SEQ, warmup_compile=True,
            ckpt_every=1000,
        ),
        client=client,
    )


def test_warmup_compile_reports_goodput_event(monkeypatch):
    train_lib.reset_build_cache()
    client = _FakeClient()
    _warmup_trainer(client)
    compile_events = [e for e in client.events if e[0] == "compile"]
    assert len(compile_events) == 1
    detail = json.loads(compile_events[0][1])
    assert detail["seconds"] > 0
    assert detail["restart"] is False
    assert detail["cached"] is False
    # A "restarted" trainer with the same (config, mesh-shape): cached,
    # zero compile seconds, restart flag from the agent env.
    monkeypatch.setenv("DLROVER_TPU_RESTART_COUNT", "1")
    client2 = _FakeClient()
    _warmup_trainer(client2)
    detail2 = json.loads(client2.events[0][1])
    assert detail2["cached"] is True
    assert detail2["seconds"] == 0.0
    assert detail2["restart"] is True


def test_persistent_compile_cache_configured(tmp_path, monkeypatch):
    from dlrover_tpu.runtime import compile_cache

    monkeypatch.delenv(compile_cache.ENV_COMPILE_CACHE, raising=False)
    # No explicit dir, no env knob, no workdir: the cache stays off.
    assert compile_cache.maybe_enable("", workdir="") is None
    cache_dir = str(tmp_path / "cc")
    enabled = compile_cache.enable(cache_dir)
    assert os.path.isdir(enabled)
    assert jax.config.jax_compilation_cache_dir == enabled
    assert compile_cache.enable(cache_dir) == enabled  # idempotent
    # Resolution order: explicit > env > workdir-derived.
    assert compile_cache.cache_dir_for("/w") == "/w/compile_cache"
    # On the CPU backend maybe_enable declines (cross-process reuse of
    # persisted CPU executables crashes a resumed trainer); the dedicated
    # opt-in env lets single-process plumbing tests through.
    monkeypatch.delenv(compile_cache.ENV_COMPILE_CACHE_CPU_OK, raising=False)
    assert compile_cache.maybe_enable("", workdir=str(tmp_path)) is None
    monkeypatch.setenv(compile_cache.ENV_COMPILE_CACHE_CPU_OK, "1")
    via_workdir = compile_cache.maybe_enable("", workdir=str(tmp_path))
    assert via_workdir == os.path.join(str(tmp_path), "compile_cache")


def test_train_cache_key_sensitivity():
    from dlrover_tpu.runtime import compile_cache

    cfg_a = gpt2_config("124m", num_layers=2, d_model=64, num_heads=2,
                        vocab_size=128, max_seq_len=SEQ)
    cfg_b = gpt2_config("124m", num_layers=2, d_model=64, num_heads=2,
                        vocab_size=128, max_seq_len=SEQ)
    key = compile_cache.train_cache_key(
        cfg_a, (8, 1), global_batch_size=8, seq_len=SEQ, optimizer="adamw"
    )
    assert key == compile_cache.train_cache_key(
        cfg_b, (8, 1), global_batch_size=8, seq_len=SEQ, optimizer="adamw"
    )
    # Any program-shaping difference must miss.
    assert key != compile_cache.train_cache_key(
        cfg_b, (4, 2), global_batch_size=8, seq_len=SEQ, optimizer="adamw"
    )
    assert key != compile_cache.train_cache_key(
        cfg_b, (8, 1), global_batch_size=16, seq_len=SEQ, optimizer="adamw"
    )
    assert key != compile_cache.train_cache_key(
        cfg_b, (8, 1), global_batch_size=8, seq_len=SEQ, optimizer="sgd"
    )


# ---------------------------------------------------------------------------
# Goodput ledger: master side
# ---------------------------------------------------------------------------


def test_speed_monitor_compile_ledger():
    from dlrover_tpu.master.speed_monitor import SpeedMonitor

    sm = SpeedMonitor()
    sm.record_compile(2.0)
    sm.record_compile(0.5, restart=True)
    sm.record_compile(0.0, restart=True, cached=True)
    ledger = sm.compile_ledger()
    assert ledger["compile_s"] == pytest.approx(2.5)
    assert ledger["restart_compile_s"] == pytest.approx(0.5)
    assert ledger["compile_events"] == 3
    assert ledger["restart_compiles"] == 2
    assert ledger["cached_compiles"] == 1


def test_servicer_routes_compile_event_to_ledger():
    from dlrover_tpu.master import messages as msg
    from dlrover_tpu.master.servicer import MasterServicer
    from dlrover_tpu.master.speed_monitor import SpeedMonitor

    sm = SpeedMonitor()
    servicer = MasterServicer(speed_monitor=sm)
    resp = servicer.report(msg.Envelope(
        node_id=0,
        payload=msg.NodeEventReport(
            node_id=0, event="compile",
            detail=json.dumps(
                {"seconds": 1.5, "restart": True, "cached": False}
            ),
        ),
    ))
    assert resp.success
    ledger = sm.compile_ledger()
    assert ledger["restart_compile_s"] == pytest.approx(1.5)
    assert ledger["restart_compiles"] == 1
    # Malformed detail must not fail the RPC nor corrupt the ledger.
    resp = servicer.report(msg.Envelope(
        node_id=0,
        payload=msg.NodeEventReport(
            node_id=0, event="compile", detail="not json",
        ),
    ))
    assert resp.success
    assert sm.compile_ledger()["compile_events"] == 1


# ---------------------------------------------------------------------------
# tools/trace_steps.py — the tier-1 pipelined-mode assertion
# ---------------------------------------------------------------------------


@pytest.mark.slow  # subprocess jax import + compile, ~5s on 1 core
def test_trace_steps_tool_zero_syncs_in_pipelined_mode():
    from tools.trace_steps import run_trace

    out = run_trace(steps=4, metrics_lag=2, prefetch=2, report_every=1)
    assert out["mode"] == "pipelined"
    assert out["summary"]["sync_block_count"] == 0
    assert out["summary"]["flush_block_count"] == 2
    assert [row["step"] for row in out["per_step"]] == [1, 2, 3, 4]
    assert all(row["sync_blocks"] == 0 for row in out["per_step"])
    # The synchronous baseline, for contrast, blocks every reported step.
    sync = run_trace(steps=3, metrics_lag=0, prefetch=0, report_every=1)
    assert sync["mode"] == "sync"
    assert sync["summary"]["sync_block_count"] == 3
