"""End-to-end sharded training tests on the virtual CPU mesh.

Covers the strategy matrix the reference exercises in
``auto_accelerate_test.py`` / ``semi_auto_acc_test.py`` (SURVEY.md §4):
DDP, FSDP, TP, SP, EP and their composition — here each strategy is just a
mesh shape, so one parameterized test covers the matrix.
"""

import jax
import numpy as np
import pytest

from dlrover_tpu.models.gpt2 import gpt2_config
from dlrover_tpu.models.llama import llama_config, moe_llama_config
from dlrover_tpu.models.transformer import TransformerLM
from dlrover_tpu.parallel import rules as lr
from dlrover_tpu.runtime.mesh import ParallelConfig, build_mesh
from dlrover_tpu.trainer import train_lib

TINY_GPT = gpt2_config(
    "124m",
    num_layers=2,
    d_model=64,
    num_heads=4,
    vocab_size=256,
    max_seq_len=64,
)


def make_batch(batch=8, seq=16, vocab=256, seed=0):
    rng = np.random.default_rng(seed)
    tokens = rng.integers(0, vocab, size=(batch, seq + 1), dtype=np.int32)
    return {"inputs": tokens[:, :-1], "targets": tokens[:, 1:]}


def run_steps(config, parallel, n_steps=3, batch=8, seq=16):
    mesh = build_mesh(parallel)
    model = TransformerLM(config)
    opt = train_lib.make_optimizer(learning_rate=1e-3)
    train = train_lib.build_sharded_train(
        model, opt, mesh, lr.DEFAULT_RULES,
        global_batch_size=batch, seq_len=seq,
    )
    state = train.init(jax.random.PRNGKey(0))
    losses = []
    # Re-feed the same batch: loss must fall as the model memorizes it.
    b = train_lib.shard_batch(make_batch(batch, seq, config.vocab_size), train)
    for _ in range(n_steps):
        state, metrics = train.step(state, b)
        losses.append(float(metrics["loss"]))
    return losses, state, train


@pytest.mark.parametrize(
    "parallel",
    [
        ParallelConfig(),                          # pure DP over 8 devices
        ParallelConfig(fsdp=8, data=1),            # ZeRO/FSDP
        ParallelConfig(tensor=2),                  # DP x TP
        ParallelConfig(fsdp=2, tensor=2),          # DP x FSDP x TP
        ParallelConfig(seq=2, tensor=2),           # DP x SP x TP (Ulysses)
    ],
    ids=["dp", "fsdp", "tp", "fsdp_tp", "sp_tp"],
)
def test_train_step_strategies(parallel):
    losses, _, _ = run_steps(TINY_GPT, parallel)
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]  # tiny model memorizes quickly


@pytest.mark.slow  # cross-compiles every strategy in one test, ~14s;
# each strategy keeps its own tier-1 witness in
# test_train_step_strategies.
def test_strategies_numerically_agree():
    """The same model must produce the same loss under any strategy."""
    losses_dp, _, _ = run_steps(TINY_GPT, ParallelConfig(), n_steps=2)
    losses_tp, _, _ = run_steps(
        TINY_GPT, ParallelConfig(fsdp=2, tensor=2), n_steps=2
    )
    np.testing.assert_allclose(losses_dp, losses_tp, rtol=2e-2)


def test_llama_variant_runs():
    cfg = llama_config(
        "tiny", num_layers=2, max_seq_len=64, vocab_size=256
    )
    losses, _, _ = run_steps(cfg, ParallelConfig(tensor=2))
    assert all(np.isfinite(losses))


@pytest.mark.slow  # superseded as tier-1 witness by the dedicated
# test_moe_trainer suite (layer-bitwise parity, compose, sharding).
def test_moe_expert_parallel():
    cfg = moe_llama_config(
        "tiny", num_experts=4, num_layers=2, max_seq_len=64, vocab_size=256
    )
    losses, _, _ = run_steps(cfg, ParallelConfig(expert=4, data=2))
    assert all(np.isfinite(losses))


def test_param_shardings_fsdp():
    """FSDP rules must actually shard the params over the fsdp axis."""
    _, state, train = run_steps(
        TINY_GPT, ParallelConfig(fsdp=8, data=1), n_steps=1
    )
    embed = state.params["embed"]["embedding"]
    spec = embed.sharding.spec
    assert "fsdp" in str(spec)


def test_remat_full():
    cfg = TINY_GPT.__class__(**{**TINY_GPT.__dict__, "remat": "full"})
    losses, _, _ = run_steps(cfg, ParallelConfig())
    assert all(np.isfinite(losses))
