"""Tier-1 self-hosting lint gate.

The shipped tree must pass its own analyzer: ``tools/tracelint.py`` over
the ``dlrover_tpu`` package (and ``tools/``) exits 0, with the checked-in
baseline allowed but expected near-empty.  The gate also asserts the run
was not vacuous — all seven rules registered and the whole package was
actually walked — so a rule-registration regression cannot masquerade as
a clean tree.

``ruff check`` runs when ruff is available; this container does not ship
it, so that leg skips with a reason rather than failing.
"""

import importlib.util
import json
import os
import shutil
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TRACELINT = os.path.join(REPO, "tools", "tracelint.py")

#: Rules the gate expects to be live; extend when adding a rule.
EXPECTED_RULES = 7


def test_tracelint_self_hosting_gate(cpu_child_env):
    proc = subprocess.run(
        [sys.executable, TRACELINT,
         os.path.join(REPO, "dlrover_tpu"), os.path.join(REPO, "tools"),
         "--json"],
        capture_output=True, text=True, timeout=300, env=cpu_child_env,
        cwd=REPO,
    )
    assert proc.returncode == 0, (
        f"tracelint found problems in the shipped tree:\n{proc.stdout}"
        f"\n{proc.stderr}"
    )
    payload = json.loads(proc.stdout)
    assert payload["rules_run"] == EXPECTED_RULES
    # The package alone is ~100 files; a collapsed walk would show here.
    assert payload["files_checked"] >= 100
    assert payload["findings"] == []


def test_shipped_baseline_is_near_empty():
    """Baselining is an escape hatch, not a dumping ground: the checked-in
    file must stay near-empty and every entry must carry a reason."""
    path = os.path.join(REPO, "tracelint_baseline.json")
    with open(path, "r", encoding="utf-8") as fh:
        data = json.load(fh)
    entries = data["findings"]
    assert len(entries) <= 3, entries
    for entry in entries:
        assert entry.get("reason", "").strip(), entry


def _ruff_command():
    if importlib.util.find_spec("ruff") is not None:
        return [sys.executable, "-m", "ruff"]
    exe = shutil.which("ruff")
    if exe:
        return [exe]
    return None


def test_ruff_clean(cpu_child_env):
    ruff = _ruff_command()
    if ruff is None:
        pytest.skip("ruff is not installed in this environment")
    proc = subprocess.run(
        [*ruff, "check", REPO],
        capture_output=True, text=True, timeout=300, env=cpu_child_env,
        cwd=REPO,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
