"""Tier-1 self-hosting lint gate.

The shipped tree must pass its own analyzer: ``tools/tracelint.py`` over
the ``dlrover_tpu`` package (and ``tools/``) exits 0, with the checked-in
baseline empty.  The gate also asserts the run was not vacuous — every
registered rule live and the whole package actually walked — so a
rule-registration regression cannot masquerade as a clean tree.

``ruff check`` runs when ruff is available; this container does not ship
it, so that leg skips with a reason rather than failing.
"""

import importlib.util
import json
import os
import shutil
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TRACELINT = os.path.join(REPO, "tools", "tracelint.py")

#: Rules the gate expects to be live; extend when adding a rule.
EXPECTED_RULES = 12


@pytest.mark.slow  # walks every repo file through all 12 rules, ~29s on 1 core
def test_tracelint_self_hosting_gate(cpu_child_env):
    proc = subprocess.run(
        [sys.executable, TRACELINT,
         os.path.join(REPO, "dlrover_tpu"), os.path.join(REPO, "tools"),
         "--json"],
        capture_output=True, text=True, timeout=300, env=cpu_child_env,
        cwd=REPO,
    )
    assert proc.returncode == 0, (
        f"tracelint found problems in the shipped tree:\n{proc.stdout}"
        f"\n{proc.stderr}"
    )
    payload = json.loads(proc.stdout)
    assert payload["rules_run"] == EXPECTED_RULES
    # The package alone is ~100 files; a collapsed walk would show here.
    assert payload["files_checked"] >= 100
    assert payload["findings"] == []


def test_shipped_baseline_is_empty():
    """Baselining is an escape hatch, not a dumping ground: the checked-in
    file ships EMPTY — live findings are fixed or inline-suppressed with a
    stated reason, never grandfathered silently."""
    path = os.path.join(REPO, "tracelint_baseline.json")
    with open(path, encoding="utf-8") as fh:
        data = json.load(fh)
    assert data["findings"] == []


def test_write_baseline_is_deterministic(tmp_path, cpu_child_env):
    """Two --write-baseline runs over the same (dirty) tree produce
    byte-identical files — no set iteration order, timestamps, or absolute
    paths may leak into the artifact, or baseline diffs churn on every CI
    run."""
    fixture_dir = tmp_path / "pkg" / "agent"
    fixture_dir.mkdir(parents=True)
    (fixture_dir / "dirty.py").write_text(textwrap.dedent(
        """
        import os
        from jax.sharding import PartitionSpec as P

        SPEC = P("dp", "tesnor")

        def persist(path, blob):
            with open(path + ".tmp", "wb") as fh:
                fh.write(blob)
            os.replace(path + ".tmp", path)
        """
    ))
    outputs = []
    for run in range(2):
        baseline = tmp_path / f"baseline_{run}.json"
        proc = subprocess.run(
            [sys.executable, TRACELINT, str(tmp_path / "pkg"),
             "--write-baseline", "--baseline", str(baseline),
             "--root", str(tmp_path)],
            capture_output=True, text=True, timeout=120,
            env=cpu_child_env, cwd=REPO,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        outputs.append(baseline.read_bytes())
    assert outputs[0] == outputs[1]
    entries = json.loads(outputs[0])["findings"]
    assert entries, "fixture should have produced baseline entries"
    rules = {e["rule"] for e in entries}
    assert "SHD001" in rules and "SEAM001" in rules


def _ruff_command():
    if importlib.util.find_spec("ruff") is not None:
        return [sys.executable, "-m", "ruff"]
    exe = shutil.which("ruff")
    if exe:
        return [exe]
    return None


def test_ruff_clean(cpu_child_env):
    ruff = _ruff_command()
    if ruff is None:
        pytest.skip("ruff is not installed in this environment")
    proc = subprocess.run(
        [*ruff, "check", REPO],
        capture_output=True, text=True, timeout=300, env=cpu_child_env,
        cwd=REPO,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
