"""Tier-1 self-hosting lint gate.

The shipped tree must pass its own analyzer: ``tools/tracelint.py`` over
the ``dlrover_tpu`` package (and ``tools/``) exits 0, with the checked-in
baseline empty.  The gate also asserts the run was not vacuous — every
registered rule live and the whole package actually walked — so a
rule-registration regression cannot masquerade as a clean tree.

``ruff check`` runs when ruff is available; this container does not ship
it, so that leg skips with a reason rather than failing.
"""

import importlib.util
import json
import os
import shutil
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TRACELINT = os.path.join(REPO, "tools", "tracelint.py")

#: Rules the gate expects to be live; extend when adding a rule.
EXPECTED_RULES = 15


@pytest.mark.slow  # walks every repo file through all 15 rules, ~30s on 1 core
def test_tracelint_self_hosting_gate(cpu_child_env):
    proc = subprocess.run(
        [sys.executable, TRACELINT,
         os.path.join(REPO, "dlrover_tpu"), os.path.join(REPO, "tools"),
         "--json"],
        capture_output=True, text=True, timeout=300, env=cpu_child_env,
        cwd=REPO,
    )
    assert proc.returncode == 0, (
        f"tracelint found problems in the shipped tree:\n{proc.stdout}"
        f"\n{proc.stderr}"
    )
    payload = json.loads(proc.stdout)
    assert payload["rules_run"] == EXPECTED_RULES
    # The package alone is ~100 files; a collapsed walk would show here.
    assert payload["files_checked"] >= 100
    assert payload["findings"] == []


def test_project_rules_self_host_clean(cpu_child_env):
    """The interprocedural rules (cache-key coverage, telemetry
    contract, locksets) pass over the live tree without the slow full
    gate: the whole-repo symbol table and call graph build in seconds,
    so this contract is checked on every non-slow run."""
    proc = subprocess.run(
        [sys.executable, TRACELINT,
         os.path.join(REPO, "dlrover_tpu"), os.path.join(REPO, "tools"),
         "--select", "CKY001,TEL001,LCK001", "--json"],
        capture_output=True, text=True, timeout=120, env=cpu_child_env,
        cwd=REPO,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout)
    assert payload["findings"] == []
    assert payload["files_checked"] >= 100


def test_cky001_resolves_both_live_cache_keys():
    """Non-vacuity probe: CKY001 is only guarding the compile-cache
    contract if it actually found and parsed the live key signatures.
    An import-graph or symbol-table regression that silently lost
    train_cache_key/serve_cache_key would otherwise read as 'clean'."""
    from dlrover_tpu.analysis.project import load_project
    from dlrover_tpu.analysis.rules.cache_keys import (
        resolve_cache_key_signatures,
    )

    project = load_project([os.path.join(REPO, "dlrover_tpu")], REPO)
    sigs = resolve_cache_key_signatures(project)
    assert set(sigs) == {"train_cache_key", "serve_cache_key"}
    train = set(sigs["train_cache_key"])
    assert {"zero1", "overlap", "allgather_quant", "donate_state",
            "grad_accum"} <= train
    serve = set(sigs["serve_cache_key"])
    assert {"tp", "spec", "attention_impl", "slots"} <= serve


def test_cky001_fires_when_fixture_key_omits_a_knob(tmp_path):
    """A knob deliberately left out of a fake cache key MUST fail —
    proves the rule has teeth, not just that the live tree is clean."""
    from dlrover_tpu.analysis import run_paths

    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "keys.py").write_text(textwrap.dedent(
        """
        def train_cache_key(model_config, mesh_shape, *,
                            global_batch_size):
            fields = tuple(sorted(vars(model_config).items()))
            return repr((fields, tuple(mesh_shape), global_batch_size))
        """
    ))
    (pkg / "build.py").write_text(textwrap.dedent(
        """
        from pkg.keys import train_cache_key

        def build_sharded_train(model, mesh, *, global_batch_size,
                                zero1=False, cache_key=None):
            key = cache_key or train_cache_key(
                model.config, mesh.shape,
                global_batch_size=global_batch_size,
            )
            return key, zero1
        """
    ))
    report = run_paths(
        [str(tmp_path)], select=["CKY001"], root=str(tmp_path)
    )
    assert any(
        f.symbol == "build_sharded_train::zero1" for f in report.findings
    ), [f.render() for f in report.findings]


def test_shipped_baseline_is_empty():
    """Baselining is an escape hatch, not a dumping ground: the checked-in
    file ships EMPTY — live findings are fixed or inline-suppressed with a
    stated reason, never grandfathered silently."""
    path = os.path.join(REPO, "tracelint_baseline.json")
    with open(path, encoding="utf-8") as fh:
        data = json.load(fh)
    assert data["findings"] == []


def test_write_baseline_is_deterministic(tmp_path, cpu_child_env):
    """Two --write-baseline runs over the same (dirty) tree produce
    byte-identical files — no set iteration order, timestamps, or absolute
    paths may leak into the artifact, or baseline diffs churn on every CI
    run."""
    fixture_dir = tmp_path / "pkg" / "agent"
    fixture_dir.mkdir(parents=True)
    (fixture_dir / "dirty.py").write_text(textwrap.dedent(
        """
        import os
        from jax.sharding import PartitionSpec as P

        SPEC = P("dp", "tesnor")

        def persist(path, blob):
            with open(path + ".tmp", "wb") as fh:
                fh.write(blob)
            os.replace(path + ".tmp", path)
        """
    ))
    (fixture_dir / "racy.py").write_text(textwrap.dedent(
        """
        import threading

        class Pump:
            def __init__(self):
                self._a_lock = threading.Lock()
                self._b_lock = threading.Lock()
                self._value = 0
                self._thread = threading.Thread(target=self._run)

            def _run(self):
                while True:
                    with self._a_lock:
                        self._value += 1

            def snapshot(self):
                with self._b_lock:
                    return self._value
        """
    ))
    outputs = []
    for run in range(2):
        baseline = tmp_path / f"baseline_{run}.json"
        proc = subprocess.run(
            [sys.executable, TRACELINT, str(tmp_path / "pkg"),
             "--write-baseline", "--baseline", str(baseline),
             "--root", str(tmp_path)],
            capture_output=True, text=True, timeout=120,
            env=cpu_child_env, cwd=REPO,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        outputs.append(baseline.read_bytes())
    assert outputs[0] == outputs[1]
    entries = json.loads(outputs[0])["findings"]
    assert entries, "fixture should have produced baseline entries"
    rules = {e["rule"] for e in entries}
    assert "SHD001" in rules and "SEAM001" in rules
    assert "LCK001" in rules, rules


def _ruff_command():
    if importlib.util.find_spec("ruff") is not None:
        return [sys.executable, "-m", "ruff"]
    exe = shutil.which("ruff")
    if exe:
        return [exe]
    return None


def test_ruff_clean(cpu_child_env):
    ruff = _ruff_command()
    if ruff is None:
        pytest.skip("ruff is not installed in this environment")
    proc = subprocess.run(
        [*ruff, "check", REPO],
        capture_output=True, text=True, timeout=300, env=cpu_child_env,
        cwd=REPO,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
