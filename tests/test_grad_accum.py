"""Microbatch engine: accumulation parity, retrace accounting, elastic
effective-batch invariance, and the quantized deferred reduce.

Parity tests use SGD: it is linear in the gradient, so the only difference
between grad_accum=N and the full-batch step is fp32 summation order.
AdamW's ``m / sqrt(v)`` normalization amplifies that reassociation noise
to ~2x the learning rate at step 1, which would force a tolerance loose
enough to be meaningless.
"""

import os

import jax
import numpy as np
import pytest

from dlrover_tpu.models.gpt2 import gpt2_config
from dlrover_tpu.models.transformer import TransformerLM
from dlrover_tpu.parallel import rules as lr
from dlrover_tpu.runtime.mesh import ParallelConfig, build_mesh
from dlrover_tpu.trainer import train_lib

import trace_asserts

TINY = gpt2_config(
    "124m", num_layers=2, d_model=64, num_heads=4,
    vocab_size=256, max_seq_len=64,
)


def _make_batch(batch=32, seq=16, vocab=256, seed=0):
    rng = np.random.default_rng(seed)
    tokens = rng.integers(0, vocab, size=(batch, seq + 1), dtype=np.int32)
    return {"inputs": tokens[:, :-1], "targets": tokens[:, 1:]}


def _build(grad_accum=1, accum_dtype="float32", reduce_quant="none",
           optimizer="sgd", batch=32, seq=16, parallel=None):
    mesh = build_mesh(parallel or ParallelConfig(data=4, fsdp=2))
    model = TransformerLM(TINY)
    opt = train_lib.make_optimizer(optimizer, learning_rate=1e-2)
    return train_lib.build_sharded_train(
        model, opt, mesh, lr.DEFAULT_RULES,
        global_batch_size=batch, seq_len=seq,
        grad_accum=grad_accum, accum_dtype=accum_dtype,
        reduce_quant=reduce_quant,
    )


def _one_step(train, batch=32, seq=16, seed=0):
    state = train.init(jax.random.PRNGKey(0))
    b = train_lib.shard_batch(
        _make_batch(batch, seq, TINY.vocab_size, seed), train
    )
    state, metrics = train.step(state, b)
    return state, {k: float(v) for k, v in metrics.items()}


def _flat_params(state):
    leaves = jax.tree.leaves(state.params)
    return np.concatenate([np.asarray(l, np.float64).ravel() for l in leaves])


def test_grad_accum_parity_fp32():
    """grad_accum=4 with an fp32 accumulator matches the full-batch step:
    loss exactly-ish (same math, different reduction order) and the SGD
    parameter update within fp32 reassociation tolerance."""
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual mesh")
    full_state, full_m = _one_step(_build(grad_accum=1))
    acc_state, acc_m = _one_step(_build(grad_accum=4))
    np.testing.assert_allclose(acc_m["loss"], full_m["loss"], rtol=1e-5)
    np.testing.assert_allclose(acc_m["tokens"], full_m["tokens"])
    np.testing.assert_allclose(
        _flat_params(acc_state), _flat_params(full_state),
        rtol=1e-4, atol=1e-5,
    )


@pytest.mark.slow  # second accumulator-dtype build, ~16s; the bf16
# accumulator is certified to the byte by the MEMORY.json temp-bytes
# gate, and fp32 parity above stays in tier-1.
def test_grad_accum_bf16_accumulator_tolerance():
    """bf16 accumulation halves accumulator HBM at the price of ~8 bits of
    mantissa per add: loss is microbatch-exact (computed in fp32 before
    the cast) but the summed gradient — hence the SGD update — only
    tracks the fp32 path to bf16 resolution (~1e-2 relative)."""
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual mesh")
    full_state, full_m = _one_step(_build(grad_accum=1))
    acc_state, acc_m = _one_step(_build(grad_accum=4, accum_dtype="bf16"))
    np.testing.assert_allclose(acc_m["loss"], full_m["loss"], rtol=1e-5)
    np.testing.assert_allclose(
        _flat_params(acc_state), _flat_params(full_state),
        rtol=2e-2, atol=2e-4,
    )


@pytest.mark.slow  # int8 reduce covered by test_zero1/test_quantized_collectives
def test_grad_accum_int8_reduce_path():
    """reduce_quant="int8" routes the deferred DP reduce through the
    block-quantized all-reduce; on data-replicated gradients the reduce is
    a quantization roundtrip, so the update stays within the int8 block
    error bound of the fp32 path."""
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual mesh")
    full_state, full_m = _one_step(_build(grad_accum=1))
    q_state, q_m = _one_step(_build(grad_accum=4, reduce_quant="int8"))
    np.testing.assert_allclose(q_m["loss"], full_m["loss"], rtol=1e-5)
    np.testing.assert_allclose(
        _flat_params(q_state), _flat_params(full_state),
        rtol=0.05, atol=1e-3,
    )


def test_grad_accum_one_retrace():
    """The scan engine compiles ONCE: repeated steps on fresh batches must
    not retrace (TRACE_COUNTS unchanged after the first step)."""
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual mesh")
    train = _build(grad_accum=4)
    state = train.init(jax.random.PRNGKey(0))

    def one_step(state, seed):
        b = train_lib.shard_batch(
            _make_batch(32, 16, TINY.vocab_size, seed), train
        )
        state, _ = train.step(state, b)
        return state

    state = one_step(state, 0)  # pays the single compilation
    with trace_asserts.assert_no_retrace("train_step"):
        for seed in (1, 2):
            state = one_step(state, seed)


def test_grad_accum_non_divisible_raises():
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual mesh")
    with pytest.raises(ValueError, match="divisible by dp\\*grad_accum"):
        _build(grad_accum=3, batch=32)  # dp=8 -> 32 % 24 != 0


def test_grad_accum_validation():
    mesh = build_mesh(ParallelConfig())
    model = TransformerLM(TINY)
    opt = train_lib.make_optimizer("sgd", learning_rate=1e-2)
    with pytest.raises(ValueError, match="grad_accum"):
        train_lib.build_sharded_train(
            model, opt, mesh, lr.DEFAULT_RULES,
            global_batch_size=16, seq_len=16, grad_accum=0,
        )
    with pytest.raises(ValueError, match="accum_dtype"):
        train_lib.build_sharded_train(
            model, opt, mesh, lr.DEFAULT_RULES,
            global_batch_size=16, seq_len=16, accum_dtype="fp8",
        )
    with pytest.raises(ValueError, match="reduce_quant"):
        train_lib.build_sharded_train(
            model, opt, mesh, lr.DEFAULT_RULES,
            global_batch_size=16, seq_len=16, reduce_quant="int4",
        )


def test_elastic_grad_accum_resolver():
    """Half the world -> double the microbatches; snapping prefers the
    next larger feasible N so per-microbatch HBM never exceeds the
    reference budget."""
    f = train_lib.elastic_grad_accum
    # Same world: unchanged.
    assert f(4, 16, 16, 256, dp=8) == 4
    # Half the chips: N doubles (tokens/step constant by construction).
    assert f(4, 16, 8, 256, dp=4) == 8
    # Double the chips: N halves.
    assert f(4, 8, 16, 256, dp=16) == 2
    # Infeasible exact target snaps UP to the next divisor.
    assert f(3, 8, 4, 16, dp=2) == 8  # target 6; divisors of 8: snap to 8
    # Target beyond every feasible N clamps to the largest.
    assert f(8, 64, 1, 16, dp=8) == 2
    # Degenerate: nothing feasible beyond N=1.
    assert f(4, 8, 4, 8, dp=8) == 1


def test_microbatch_phase_plan_covers_step():
    rows = train_lib.microbatch_phase_plan(4, "int8", 1.0)
    accum = [r for r in rows if r["phase"] == "accumulate"]
    assert [r["micro"] for r in accum] == [0, 1, 2, 3]
    assert {r["phase"] for r in rows} == {"accumulate", "reduce", "update"}
    total = sum(r["dur"] for r in rows)
    np.testing.assert_allclose(total, 1.0, rtol=1e-6)
    # int8 wire prices the reduce cheaper than full precision.
    full = train_lib.microbatch_phase_plan(4, "none", 1.0)
    dur = lambda rs: next(r["dur"] for r in rs if r["phase"] == "reduce")
    assert dur(rows) < dur(full)


def test_cache_key_includes_accum_knobs():
    from dlrover_tpu.runtime.compile_cache import train_cache_key

    base = dict(
        global_batch_size=16, seq_len=16, optimizer="sgd",
    )
    k1 = train_cache_key(TINY, (4, 2), **base)
    k2 = train_cache_key(TINY, (4, 2), **base, grad_accum=4)
    k3 = train_cache_key(
        TINY, (4, 2), **base, grad_accum=4, reduce_quant="int8"
    )
    k4 = train_cache_key(
        TINY, (4, 2), **base, grad_accum=4, accum_dtype="bf16"
    )
    assert len({k1, k2, k3, k4}) == 4


@pytest.mark.slow  # full save/resize/restore drill, ~11s; the resize
# invariance plane keeps its tier-1 witnesses in test_resize
# (cross_world_restore_matrix, preempt_resume trajectory).
def test_elastic_trainer_resize_invariance(tmp_path, monkeypatch):
    """A 'resize' (reference world 16 -> actual world 8) rescales
    grad_accum so tokens/step is invariant, and the booked reference in
    the checkpoint extra survives a restore into a fresh trainer."""
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual mesh")
    from dlrover_tpu.trainer.elastic_trainer import (
        ElasticTrainer,
        TrainerConfig,
    )

    monkeypatch.setenv(
        "DLROVER_TPU_JOB", f"ga{os.getpid()}_{tmp_path.name}"
    )
    monkeypatch.setenv("DLROVER_TPU_SOCKET_DIR", str(tmp_path / "socks"))

    def loader(n, batch=32, seq=32, seed=0):
        rng = np.random.default_rng(seed)
        for _ in range(n):
            t = rng.integers(0, 256, size=(batch, seq + 1), dtype=np.int32)
            yield {"inputs": t[:, :-1], "targets": t[:, 1:]}

    cfg = gpt2_config(
        "124m", num_layers=1, d_model=64, num_heads=2,
        vocab_size=256, max_seq_len=32,
    )
    common = dict(
        global_batch_size=32, seq_len=32, optimizer="sgd",
        learning_rate=1e-2, checkpoint_dir=str(tmp_path / "ckpt"),
        ckpt_every=2,
    )
    # "Before the resize": grad_accum=2 booked at a 16-chip world.
    first = ElasticTrainer(
        cfg,
        TrainerConfig(**common, grad_accum=2, grad_accum_ref_world=16),
        client=None,
    )
    # The 8-device world is half the reference: N doubles, tokens/step
    # (= global_batch x seq) is unchanged by construction.
    assert first.train.grad_accum == 4
    tokens_before = first.config.global_batch_size * first.config.seq_len
    first.fit(loader(4), max_steps=2)
    extra = first._accum_extra()
    first.close()
    assert extra["grad_accum_ref"] == {"accum": 2, "world": 16}

    # "After the restart": a fresh trainer with NO accum config adopts the
    # booked reference from the checkpoint and resolves the same N.
    second = ElasticTrainer(cfg, TrainerConfig(**common), client=None)
    try:
        assert second.step == 2
        assert second.train.grad_accum == 4
        tokens_after = (
            second.config.global_batch_size * second.config.seq_len
        )
        assert tokens_after == tokens_before
    finally:
        second.close()
