"""Ring attention (context parallelism) vs single-device reference."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlrover_tpu.models.attention import xla_attention
from dlrover_tpu.parallel.ring_attention import ring_attention
from dlrover_tpu.runtime.mesh import (
    ParallelConfig,
    activate_mesh,
    build_mesh,
)


@pytest.fixture()
def seq4_mesh():
    return build_mesh(ParallelConfig(data=2, seq=4))


@pytest.mark.parametrize("causal", [True, False])
def test_ring_matches_reference(rng, seq4_mesh, causal):
    b, s, h, d = 2, 64, 4, 32
    q = jnp.asarray(rng.normal(size=(b, s, h, d)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(b, s, h, d)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(b, s, h, d)), jnp.float32)
    with activate_mesh(seq4_mesh):
        out = jax.jit(
            functools.partial(ring_attention, causal=causal)
        )(q, k, v)
    ref = xla_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_ring_segments_and_gqa(rng, seq4_mesh):
    b, s, hq, hkv, d = 2, 64, 4, 2, 32
    q = jnp.asarray(rng.normal(size=(b, s, hq, d)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(b, s, hkv, d)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(b, s, hkv, d)), jnp.float32)
    seg = jnp.asarray((np.arange(s) // 16)[None].repeat(b, 0), jnp.int32)
    with activate_mesh(seq4_mesh):
        out = jax.jit(
            functools.partial(ring_attention, causal=True)
        )(q, k, v, segment_ids=seg)
    ref = xla_attention(q, k, v, causal=True, segment_ids=seg)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_ring_grads(rng, seq4_mesh):
    b, s, h, d = 2, 64, 2, 32
    q = jnp.asarray(rng.normal(size=(b, s, h, d)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(b, s, h, d)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(b, s, h, d)), jnp.float32)

    with activate_mesh(seq4_mesh):
        g_ring = jax.jit(jax.grad(
            lambda q, k, v: jnp.sum(ring_attention(q, k, v, causal=True) ** 2),
            argnums=(0, 1, 2),
        ))(q, k, v)
    g_ref = jax.grad(
        lambda q, k, v: jnp.sum(xla_attention(q, k, v, causal=True) ** 2),
        argnums=(0, 1, 2),
    )(q, k, v)
    for gr, gx, name in zip(g_ring, g_ref, "qkv"):
        np.testing.assert_allclose(
            np.asarray(gr), np.asarray(gx), atol=5e-5, rtol=5e-5,
            err_msg=f"d{name}",
        )


@pytest.mark.slow  # full ring-attention model build; kernel parity is unit-tested above
def test_ring_model_end_to_end(rng):
    """Full TransformerLM with attention_impl='ring' trains under a seq mesh."""
    from dlrover_tpu.models.gpt2 import gpt2_config
    from dlrover_tpu.models.transformer import TransformerLM
    from dlrover_tpu.parallel import rules as lr
    from dlrover_tpu.trainer import train_lib

    cfg = gpt2_config(
        "124m", num_layers=2, d_model=64, num_heads=4,
        vocab_size=256, max_seq_len=64, attention_impl="ring",
    )
    mesh = build_mesh(ParallelConfig(data=2, seq=4))
    model = TransformerLM(cfg)
    opt = train_lib.make_optimizer(learning_rate=1e-3)
    train = train_lib.build_sharded_train(
        model, opt, mesh, lr.RING_RULES, global_batch_size=4, seq_len=64
    )
    state = train.init(jax.random.PRNGKey(0))
    toks = rng.integers(0, 256, size=(4, 65), dtype=np.int32)
    batch = train_lib.shard_batch(
        {"inputs": toks[:, :-1], "targets": toks[:, 1:]}, train
    )
    losses = []
    for _ in range(3):
        state, metrics = train.step(state, batch)
        losses.append(float(metrics["loss"]))
    assert np.isfinite(losses).all() and losses[-1] < losses[0]
