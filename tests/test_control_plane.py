"""Master/agent control-plane tests over in-process localhost gRPC.

Mirrors the reference's test strategy (SURVEY.md §4: real agent against an
in-process master + servicer; multi-node behavior by simulating node ranks
joining the rendezvous manager directly).
"""

import time

import pytest

from dlrover_tpu.agent.master_client import MasterClient
from dlrover_tpu.master import messages as msg
from dlrover_tpu.master.job_master import JobMaster
from dlrover_tpu.master.rdzv_manager import (
    ElasticTrainingRendezvousManager,
    NetworkCheckRendezvousManager,
)
from dlrover_tpu.master.speed_monitor import SpeedMonitor


@pytest.fixture(scope="module")
def master():
    m = JobMaster(port=0, num_nodes=2)
    m.start()
    yield m
    m.stop()


@pytest.fixture()
def client(master):
    c = MasterClient(f"localhost:{master.port}", node_id=0)
    yield c
    c.close()


def test_rendezvous_two_nodes(master, client):
    client2 = MasterClient(f"localhost:{master.port}", node_id=1)
    assert client.join_rendezvous(0, 4) == 0
    state = client.get_comm_world(0)
    assert state.world == {}  # still forming: only 1 of 2 nodes
    client2.join_rendezvous(1, 4)
    state = client.get_comm_world(0)
    assert state.world == {0: 4, 1: 4}
    assert state.round == 1
    state2 = client2.get_comm_world(1)
    assert state2.world == {0: 4, 1: 4}
    client2.close()


def test_dynamic_sharding_and_recovery(master, client):
    client.create_dataset(
        msg.DatasetShardParams(
            dataset_name="train", dataset_size=100, shard_size=30
        )
    )
    seen = []
    t1 = client.get_task("train")
    t2 = client.get_task("train")
    seen += [(t1.start, t1.end), (t2.start, t2.end)]
    client.report_task("train", t1.task_id, success=True)
    # node 0 dies with t2 in flight -> shard requeues
    master.task_manager.recover_tasks(0)
    t3 = client.get_task("train")
    assert (t3.start, t3.end) == (t2.start, t2.end)
    # drain the rest
    tasks = []
    while True:
        t = client.get_task("train")
        if t.empty:
            break
        tasks.append(t)
        client.report_task("train", t.task_id)
    client.report_task("train", t3.task_id)
    covered = sorted(seen + [(t.start, t.end) for t in tasks])
    assert covered[0][0] == 0 and covered[-1][1] == 100


def test_shard_checkpoint_roundtrip(master, client):
    client.create_dataset(
        msg.DatasetShardParams(
            dataset_name="ckpt_ds", dataset_size=60, shard_size=20
        )
    )
    t = client.get_task("ckpt_ds")  # one in flight
    ckpt = client.get_shard_checkpoint("ckpt_ds")
    assert "todo" in ckpt.content
    client.restore_shard_checkpoint(ckpt)
    # after restore, the in-flight shard is pending again
    starts = set()
    while True:
        task = client.get_task("ckpt_ds")
        if task.empty:
            break
        starts.add(task.start)
        client.report_task("ckpt_ds", task.task_id)
    assert t.start in starts


def test_kv_store_and_barrier(master, client):
    client.kv_put("rdzv/addr", b"10.0.0.1:1234")
    assert client.kv_get("rdzv/addr") == b"10.0.0.1:1234"
    assert client.kv_get("missing") is None
    assert client.kv_add("barrier/x") == 1
    assert client.kv_add("barrier/x") == 2


def test_step_reports_and_job_status(master, client):
    now = time.time()
    for i, step in enumerate([1, 2, 3, 4]):
        master.speed_monitor.collect_global_step(
            step, now + i * 1.0, tokens=1000
        )
    status = client.get_job_status()
    assert status.global_step == 4
    assert status.speed == pytest.approx(1.0, rel=0.2)


def test_failure_report_actions(master, client):
    action = client.report_failure("oom", exit_code=137, level="process")
    assert action == "restart"
    action = client.report_failure("host gone", exit_code=1, level="node")
    assert action == "relaunch"


def test_network_check_bisection():
    manager = NetworkCheckRendezvousManager()
    manager.update_rdzv_params(4, 4, 60.0, 1)
    for rank in range(4):
        manager.join_rendezvous(rank, 4)
    # round 0: pairs (0,1) (2,3)
    _, g0, w0 = manager.get_comm_world(0)
    _, g1, w1 = manager.get_comm_world(2)
    assert set(w0) == {0, 1} and set(w1) == {2, 3}
    assert g0 != g1
    # pair (2,3) fails its probe
    manager.report_network_status(0, True, 1.0)
    manager.report_network_status(1, True, 1.0)
    manager.report_network_status(2, False, 1.0)
    manager.report_network_status(3, False, 1.0)
    faults, reason = manager.check_fault_node()
    assert set(faults) == {2, 3}
    # round 1: each suspect paired with a healthy node to bisect
    groups = manager._group_nodes(1)
    for suspect in (2, 3):
        group = [g for g in groups if suspect in g][0]
        assert any(r in (0, 1) for r in group), group
    # after round 1, only node 3 still fails -> node 3 is the bad host
    manager.report_network_status(2, True, 1.0)
    manager.report_network_status(3, False, 1.0)
    faults, _ = manager.check_fault_node()
    assert faults == [3]


def test_straggler_detection():
    manager = NetworkCheckRendezvousManager()
    manager.update_rdzv_params(4, 4, 60.0, 1)
    for rank in range(4):
        manager.join_rendezvous(rank, 1)
        manager.get_comm_world(rank)
    times = {0: 1.0, 1: 1.1, 2: 0.9, 3: 5.0}
    for rank, t in times.items():
        manager.report_network_status(rank, True, t)
    assert manager.get_stragglers() == [3]


def test_rdzv_node_unit_rounding():
    """With node_unit=2, a 3-node waiting set seals a 2-node world."""
    manager = ElasticTrainingRendezvousManager()
    manager.update_rdzv_params(
        min_nodes=2, max_nodes=4, waiting_timeout=0.0, node_unit=2
    )
    for rank in range(3):
        manager.join_rendezvous(rank, 4)
    time.sleep(0.01)
    _, _, world = manager.get_comm_world(0)
    assert len(world) == 2


def test_speed_monitor_goodput():
    monitor = SpeedMonitor()
    t0 = time.time()
    monitor.collect_global_step(1, t0)
    monitor.collect_global_step(2, t0 + 1)
    assert monitor.no_progress_for() < 5
    assert 0.0 <= monitor.goodput() <= 1.0


def test_network_check_odd_healthy_pool_no_singleton():
    """ADVICE low: round>=1 grouping with an odd healthy pool must not
    strand the last node in a singleton/empty comm world."""
    from dlrover_tpu.master.rdzv_manager import NetworkCheckRendezvousManager

    mgr = NetworkCheckRendezvousManager()
    mgr._rdzv_nodes = {r: 1 for r in range(5)}
    for r in range(5):
        mgr._node_status[r] = True  # all healthy -> pool of 5, no suspects
    groups = mgr._group_nodes(check_round=1)
    covered = sorted(r for g in groups for r in g)
    assert covered == list(range(5))
    assert all(len(g) >= 2 for g in groups)


def test_sync_service_barrier_and_cluster_version(master, client):
    client2 = MasterClient(f"localhost:{master.port}", node_id=1)
    assert client.join_sync("init", need=2) is False
    assert client2.join_sync("init", need=2) is True
    # Late (re-)join of a finished barrier passes immediately.
    assert client.join_sync("init", need=2) is True
    assert client.sync_finished("init")

    # Cluster version: global = min over reporters, gated on the expected
    # reporter count (one early reporter must not advance it alone).
    assert client.report_cluster_version(3, expected=2) == 0
    assert client2.report_cluster_version(2, expected=2) == 2
    assert client.get_cluster_version() == 2
    # A dead node must not hold the version back or wedge barriers.
    client.join_sync("resize", need=2)
    master._handle_node_death(1)
    assert client.sync_finished("resize")
    assert client.report_cluster_version(3, expected=1) == 3
    client2.close()


def test_paral_config_update_and_versioning(master, client):
    from dlrover_tpu.master import messages as msg

    base = client.get_paral_config()
    master.servicer.update_paral_config(
        msg.ParalConfig(global_batch_size=64, grad_accum=2)
    )
    updated = client.get_paral_config()
    assert updated.version == base.version + 1
    assert updated.global_batch_size == 64


def test_master_kill_restart_agents_rejoin_monotonic_round(tmp_path):
    """Satellite: kill the master (stop; only state_path survives), start
    a fresh one from the same state file, and have the agents re-join over
    the wire — the re-formed world's rendezvous round must be strictly
    greater than any round the dead master sealed, so agents can tell the
    re-join from a stale world."""
    path = str(tmp_path / "master_state.json")
    first = JobMaster(port=0, num_nodes=2, min_nodes=1, state_path=path)
    first.start()
    try:
        a0 = MasterClient(f"localhost:{first.port}", node_id=0)
        a1 = MasterClient(f"localhost:{first.port}", node_id=1)
        a0.join_rendezvous(0, 4)
        a1.join_rendezvous(1, 4)
        sealed = a0.get_comm_world(0)
        assert sealed.round == 1 and sealed.world == {0: 4, 1: 4}
        first._state_store.save(first)
        a0.close()
        a1.close()
    finally:
        first.stop()  # the kill: all in-memory state gone

    fresh = JobMaster(port=0, num_nodes=2, min_nodes=1, state_path=path)
    fresh.start()
    try:
        # Restore alone already keeps the counter monotonic...
        assert fresh.rdzv_managers["elastic-training"]._rdzv_round >= 1
        a0 = MasterClient(f"localhost:{fresh.port}", node_id=0)
        a1 = MasterClient(f"localhost:{fresh.port}", node_id=1)
        a0.join_rendezvous(0, 4)
        a1.join_rendezvous(1, 4)
        resealed = a0.get_comm_world(0)
        # ...and the agents' re-join seals a STRICTLY newer round.
        assert resealed.world == {0: 4, 1: 4}
        assert resealed.round > sealed.round
        a0.close()
        a1.close()
    finally:
        fresh.stop()
