"""Sentry: silent-data-corruption detection end to end, minus the drill.

Covers the four layers at unit/integration scope:

* **digest** — determinism, layout-independence (replicated vs zero1),
  and sensitivity: one flipped mantissa bit changes the digest under
  EVERY build shape (grad-accum on/off, int8 reduce), deterministically.
* **vote** — the speed monitor's watermark-finalized majority vote with
  node attribution, streak bookkeeping, and tie handling.
* **decide** — SDCVoteOperator thresholds (confirm REPORT vs QUARANTINE)
  and the master's quarantine execution: blacklist, rendezvous ban,
  replacement launch, state-store persistence across a master restart.
* **trainer** — the check rides the step span at its cadence with zero
  retraces, and ships digests on the report cadence.

The chaos certifier (inject -> vote -> quarantine -> restore on live
agents) lives in ``tools/goodput_bench.py --sdc-drill``.
"""

import time

import jax
import numpy as np
import pytest

from dlrover_tpu.common import faults
from dlrover_tpu.master import messages as msg
from dlrover_tpu.master.cloud_launcher import (
    CloudNodeLauncher,
    FakeTpuVmClient,
)
from dlrover_tpu.master.diagnosis import (
    ActionType,
    DiagnosisContext,
    InferenceChain,
    SDCVoteOperator,
)
from dlrover_tpu.master.job_master import JobMaster
from dlrover_tpu.master.node_manager import NodeManager, NodeStatus
from dlrover_tpu.master.servicer import MasterServicer
from dlrover_tpu.master.speed_monitor import SpeedMonitor
from dlrover_tpu.models.gpt2 import gpt2_config
from dlrover_tpu.models.transformer import TransformerLM
from dlrover_tpu.parallel import rules as lr
from dlrover_tpu.runtime.mesh import ParallelConfig, build_mesh
from dlrover_tpu.trainer import state_digest, train_lib

import trace_asserts

TINY = gpt2_config(
    "124m", num_layers=2, d_model=64, num_heads=4,
    vocab_size=256, max_seq_len=64,
)


def _make_batch(batch=32, seq=16, vocab=256, seed=0):
    rng = np.random.default_rng(seed)
    tokens = rng.integers(0, vocab, size=(batch, seq + 1), dtype=np.int32)
    return {"inputs": tokens[:, :-1], "targets": tokens[:, 1:]}


def _build(zero1=False, grad_accum=1, reduce_quant="none",
           batch=32, seq=16, parallel=None):
    mesh = build_mesh(parallel or ParallelConfig(data=4, fsdp=2))
    model = TransformerLM(TINY)
    opt = train_lib.make_optimizer("sgd", learning_rate=1e-2)
    return train_lib.build_sharded_train(
        model, opt, mesh, lr.DEFAULT_RULES,
        global_batch_size=batch, seq_len=seq,
        grad_accum=grad_accum, reduce_quant=reduce_quant, zero1=zero1,
    )


def _digest(train, state) -> str:
    return state_digest.format_digest(
        state_digest.build_digest_fn(train)(state)
    )


def _needs_mesh():
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual mesh")


# -- digest: determinism, layout-independence, sensitivity --------------------


def test_digest_deterministic_and_layout_independent():
    """Identical state => identical digest, including ACROSS shardings:
    the replicated and zero1 builds init to bitwise-equal state (see
    test_zero1.py's rationale), and the uint32 byte-sum fold is exact
    integer arithmetic, so the layout cannot perturb the value."""
    _needs_mesh()
    full = _build()
    z = _build(zero1=True)
    d_full = _digest(full, full.init(jax.random.PRNGKey(0)))
    d_z = _digest(z, z.init(jax.random.PRNGKey(0)))
    assert d_full == d_z
    # Re-digesting the same state is stable.
    assert d_full == _digest(full, full.init(jax.random.PRNGKey(0)))
    assert len(d_full) == 8 and int(d_full, 16) >= 0


@pytest.mark.parametrize(
    "zero1,grad_accum,reduce_quant",
    [
        (False, 1, "none"),
        (True, 1, "none"),
        (False, 4, "none"),
        # The int8 build compiles the quantized reduce on top, ~11s on
        # 1 core; the transport itself is covered in test_zero1.
        pytest.param(True, 4, "int8", marks=pytest.mark.slow),
    ],
)
def test_flip_is_exactly_one_outlier_under_every_build(
    zero1, grad_accum, reduce_quant
):
    """Under every build shape, post-step replicas digest identically, a
    single ``sdc.flip`` makes exactly ONE outlier, and the flip is
    deterministic: rerunning with the same coordinates reproduces the
    same corrupted digest."""
    _needs_mesh()
    train = _build(
        zero1=zero1, grad_accum=grad_accum, reduce_quant=reduce_quant
    )
    state = train.init(jax.random.PRNGKey(0))
    batch = train_lib.shard_batch(_make_batch(), train)
    state, _ = train.step(state, batch)
    clean = _digest(train, state)

    flipped = state_digest.flip_mantissa_bit(
        state, bit=10, leaf_index=1, flat_index=3
    )
    corrupt = _digest(train, flipped)
    assert corrupt != clean
    # Determinism with the same fault coordinates (what a seeded plan
    # replays): the corrupted digest is reproducible bit for bit.
    again = state_digest.flip_mantissa_bit(
        state, bit=10, leaf_index=1, flat_index=3
    )
    assert _digest(train, again) == corrupt
    # An XOR flip is an involution: flipping the same bit back restores
    # the clean digest exactly.
    restored = state_digest.flip_mantissa_bit(
        flipped, bit=10, leaf_index=1, flat_index=3
    )
    assert _digest(train, restored) == clean

    # Three replicas vote: the flipped one is the single outlier.
    sm = SpeedMonitor()
    for node, digest in enumerate([clean, clean, corrupt]):
        sm.record_digest(node, step=16, digest=digest)
    sm.record_digest(0, step=32, digest=clean)  # watermark finalizes 16
    ledger = sm.sdc_ledger()
    assert ledger["checks"] == 1 and ledger["mismatches"] == 1
    assert ledger["streaks"] == {2: 1}


def test_flip_fires_through_the_fault_seam():
    """``sdc.flip`` is a registered Faultline seam: a plan arms it and a
    seeded run fires it at the same hit every rerun."""
    assert "sdc.flip" in faults.KNOWN_SEAMS
    for _ in range(2):
        plan = faults.configure("sdc.flip:error@2", seed=11)
        try:
            faults.fire("sdc.flip", step=1)  # hit 1: armed for hit 2 only
            with pytest.raises(faults.FaultInjected) as e:
                faults.fire("sdc.flip", step=2)
            assert e.value.seam == "sdc.flip" and e.value.hit == 2
            faults.fire("sdc.flip", step=3)  # one-shot: no further fires
            assert plan.fired == [("sdc.flip", "error", 2)]
        finally:
            faults.configure("")


def test_digest_no_retrace_at_check_cadence():
    """The digest program compiles once; steps interleaved with digest
    calls at the check cadence trigger ZERO fresh traces of either."""
    _needs_mesh()
    train = _build()
    state = train.init(jax.random.PRNGKey(0))
    digest_fn = state_digest.build_digest_fn(train)

    def one_step(state, seed):
        b = train_lib.shard_batch(
            _make_batch(seed=seed), train
        )
        state, _ = train.step(state, b)
        return state

    state = one_step(state, 0)       # pays the single step compilation
    digest_fn(state).block_until_ready()  # pays the digest compilation
    with trace_asserts.assert_no_retrace("train_step", "state_digest"):
        seen = set()
        for seed in (1, 2, 3):
            state = one_step(state, seed)
            seen.add(state_digest.format_digest(digest_fn(state)))
    assert len(seen) == 3  # the state (and digest) moved every step


# -- vote: the speed monitor ledger -------------------------------------------


def test_vote_watermark_is_per_node():
    sm = SpeedMonitor()
    sm.record_digest(0, 16, "aa", check_every=16)
    sm.record_digest(1, 16, "aa")
    # Nothing newer yet: step 16 is still pending.
    assert sm.sdc_ledger()["checks"] == 0
    # Only node 0 moving past 16 must NOT finalize it: node 1's replica
    # may run a full report cadence behind (restarts skew replicas by
    # minutes), and a global watermark would drop its vote.
    sm.record_digest(0, 32, "bb")
    assert sm.sdc_ledger()["checks"] == 0
    sm.record_digest(1, 32, "bb")
    ledger = sm.sdc_ledger()
    assert ledger["checks"] == 1 and ledger["mismatches"] == 0
    assert ledger["streaks"] == {} and ledger["check_every"] == 16


def test_vote_stale_reporter_does_not_stall_the_pipeline():
    sm = SpeedMonitor()
    # Node 1 votes once and vanishes (crash without quarantine); node 0
    # keeps checking.  Four check intervals past the fastest reporter,
    # stale steps force-finalize so detection never deadlocks.
    sm.record_digest(0, 16, "aa", check_every=16)
    sm.record_digest(1, 16, "aa")
    for step in (32, 48, 64, 80):
        sm.record_digest(0, step, "aa")
    assert sm.sdc_ledger()["checks"] == 0
    sm.record_digest(0, 96, "aa")  # 16 is now > 4 checks stale
    assert sm.sdc_ledger()["checks"] == 1


def test_vote_single_report_step_dropped():
    sm = SpeedMonitor()
    sm.record_digest(0, 16, "aa")
    sm.record_digest(0, 32, "aa")  # finalizes 16 with one vote: no info
    assert sm.sdc_ledger()["checks"] == 0


def test_vote_streak_accumulates_and_resets():
    sm = SpeedMonitor()
    # Two consecutive checks with node 2 in the minority.
    for step, bad in ((16, "xx"), (32, "yy")):
        for node in (0, 1):
            sm.record_digest(node, step, f"good{step}")
        sm.record_digest(2, step, bad)
    sm.record_digest(0, 48, "good48")
    assert sm.sdc_ledger()["streaks"] == {2: 2}
    assert sm.sdc_ledger()["mismatches"] == 2
    # A clean check resets the streak: corruption must be persistent.
    for node in (0, 1, 2):
        sm.record_digest(node, 48, "good48")
    sm.record_digest(0, 64, "good64")
    assert sm.sdc_ledger()["streaks"] == {}


def test_vote_two_way_tie_trusts_neither():
    sm = SpeedMonitor()
    sm.record_digest(0, 16, "aa")
    sm.record_digest(1, 16, "bb")
    sm.record_digest(0, 32, "aa")
    ledger = sm.sdc_ledger()
    # A 1-1 split has no majority to trust: booked as a check, not a
    # mismatch, and nobody's streak moves.
    assert ledger["checks"] == 1 and ledger["mismatches"] == 0
    assert ledger["streaks"] == {}


def test_quarantine_clears_ledger_state():
    sm = SpeedMonitor()
    for node in (0, 1):
        sm.record_digest(node, 16, "good")
    sm.record_digest(2, 16, "bad")
    sm.record_digest(0, 32, "good")
    sm.record_digest(2, 32, "bad2")  # pending vote from the corrupt node
    sm.record_sdc_quarantine(2)
    ledger = sm.sdc_ledger()
    assert ledger["quarantines"] == 1 and ledger["streaks"] == {}
    # The quarantined node's pending vote is gone: once nodes 0/1 finalize
    # step 32 it cannot re-enter the tally.
    sm.record_digest(1, 32, "good")
    sm.record_digest(0, 48, "good")
    assert sm.sdc_ledger()["mismatches"] == 1  # still just the step-16 one


def test_digest_report_routes_through_servicer():
    sm = SpeedMonitor()
    servicer = MasterServicer(speed_monitor=sm)
    for node in (0, 1):
        env = msg.Envelope(
            node_id=node,
            payload=msg.DigestReport(node, 16, "cafe0123", check_every=16),
        )
        assert servicer.report(env).success
    for node in (0, 1):
        servicer.report(msg.Envelope(
            node_id=node, payload=msg.DigestReport(node, 32, "cafe0123"),
        ))
    assert sm.sdc_ledger()["checks"] == 1


# -- decide: operator thresholds and the master's quarantine path -------------


def _ctx(sm):
    return DiagnosisContext(
        speed_monitor=sm, metrics=None, node_manager=None, timeline=None,
    )


def _feed_minority(sm, steps, bad_node=2, nodes=3):
    for step in steps:
        for node in range(nodes):
            digest = f"bad{step}" if node == bad_node else f"good{step}"
            sm.record_digest(node, step, digest)
    sm.record_digest(0, max(steps) + 16, "next")


def test_operator_transient_mismatch_asks_for_confirm_probe():
    sm = SpeedMonitor()
    _feed_minority(sm, [16])
    actions = SDCVoteOperator().observe(_ctx(sm))
    assert [a.action for a in actions] == [ActionType.REPORT]
    assert "confirm probe" in actions[0].reason
    assert actions[0].node_id == 2


def test_operator_persistent_minority_quarantines():
    sm = SpeedMonitor()
    _feed_minority(sm, [16, 32])
    op = SDCVoteOperator()
    actions = op.observe(_ctx(sm))
    assert [a.action for a in actions] == [ActionType.QUARANTINE]
    assert actions[0].node_id == 2 and actions[0].severity == 4
    assert "minority" in actions[0].reason


def test_operator_latch_quiets_stale_mismatches():
    sm = SpeedMonitor()
    _feed_minority(sm, [16])
    op = SDCVoteOperator()
    assert op.observe(_ctx(sm))          # fresh: confirm REPORT
    assert op.observe(_ctx(sm)) == []    # same count: consumed, silent


def test_operator_registered_in_default_chain():
    assert any(
        isinstance(op, SDCVoteOperator) for op in InferenceChain().operators
    )


def test_master_quarantine_blacklists_bans_and_replaces():
    """The full QUARANTINE execution: node blacklisted (never relaunched),
    banned from rendezvous re-join, replacement launched at a fresh id
    with the target unchanged, ledger bumped."""
    client = FakeTpuVmClient()
    launcher = CloudNodeLauncher(client, job_name="job")
    master = JobMaster(num_nodes=2, launcher=launcher, auto_scale=True,
                       heartbeat_timeout=3600.0)
    try:
        nm = master.node_manager
        master.bootstrap_nodes()
        deadline = time.monotonic() + 5.0
        while len(client.create_calls) < 2 and time.monotonic() < deadline:
            time.sleep(0.02)
        for n in range(2):
            nm.report_event(n, "started")
        elastic = master.rdzv_managers["elastic-training"]
        for n in range(2):
            elastic.join_rendezvous(n, 1)

        master._quarantine_node(1, "digest minority x2")

        assert nm.is_quarantined(1)
        assert nm.quarantined() == {1: "digest minority x2"}
        assert not nm.relaunchable(1)
        assert not nm.launch_node(1)          # blacklist sticks
        assert not nm.force_relaunch(1)
        assert nm.statuses()[1] == NodeStatus.FAILED.value
        # Rendezvous ban: a re-join attempt is refused (no waiting entry).
        round_before = elastic._rdzv_round
        elastic.join_rendezvous(1, 1)
        assert 1 not in elastic._alive_nodes
        assert elastic._rdzv_round >= round_before
        # Replacement minted at a fresh id, target unchanged.
        deadline = time.monotonic() + 5.0
        while (
            "job-worker-2" not in client.create_calls
            and time.monotonic() < deadline
        ):
            time.sleep(0.02)
        assert "job-worker-2" in client.create_calls
        assert master.auto_scaler.target == 2
        assert master.speed_monitor.sdc_ledger()["quarantines"] == 1
        # The snapshot carries the verdict for the state store.
        snap = nm.snapshot()[1]
        assert snap["quarantined"] and "minority" in snap["quarantine_reason"]
    finally:
        master.stop()
        launcher.shutdown()


def test_quarantine_does_not_wedge_job_completion():
    nm = NodeManager(num_nodes=2)
    nm.report_event(0, "started")
    nm.report_event(1, "started")
    nm.quarantine(1, "sdc")
    nm.report_event(0, "succeeded")
    # The quarantined node can never succeed; the job must still complete.
    assert nm.all_succeeded()


def test_quarantine_survives_master_restart(tmp_path):
    """Satellite: the state store round-trips the blacklist — a restarted
    master cannot re-admit a quarantined node."""
    path = str(tmp_path / "master_state.json")
    master = JobMaster(num_nodes=2, min_nodes=1, state_path=path)
    try:
        master.node_manager.ensure_node(1)
        master._quarantine_node(1, "digest minority x2")
        master._state_store.save(master)
    finally:
        master.stop()

    fresh = JobMaster(num_nodes=2, min_nodes=1, state_path=path)
    try:
        fresh.start()
        assert fresh.node_manager.is_quarantined(1)
        assert fresh.node_manager.quarantined()[1] == "digest minority x2"
        assert not fresh.node_manager.relaunchable(1)
        elastic = fresh.rdzv_managers["elastic-training"]
        elastic.join_rendezvous(1, 1)  # refused: the ban was restored
        assert 1 not in elastic._alive_nodes
    finally:
        fresh.stop()


def test_serve_and_resize_ledgers_survive_master_restart(tmp_path):
    """Satellite: the serve ledger (incl. hot-swap counters) and the resize
    ledger's per-kind seconds split round-trip the state store — a master
    restart must not read as a counter reset on the ``dlrover_serve_*`` /
    ``dlrover_resize_seconds_total{kind=...}`` gauges."""
    path = str(tmp_path / "master_state.json")
    master = JobMaster(num_nodes=1, min_nodes=1, state_path=path)
    try:
        sm = master.speed_monitor
        sm.record_serve(
            0, qps=4.0, p95_s=0.25, occupancy=0.5, slots=4.0,
            requests=12.0, tokens=96.0,
        )
        sm.record_swap(0, version=3, ok=True, seconds=0.2)
        sm.record_swap(0, version=3, ok=False, rolled_back=True, seconds=0.1)
        sm.record_relayout(0.05, ok=True)
        sm.begin_resize("preempt", kind="restore")
        sm.collect_global_step(10, tokens=1)  # closes the open window
        master._state_store.save(master)
    finally:
        master.stop()

    fresh = JobMaster(num_nodes=1, min_nodes=1, state_path=path)
    try:
        fresh.start()
        serve = fresh.speed_monitor.serve_ledger()
        assert serve["qps"] == 4.0
        assert serve["p95_s"] == 0.25
        assert serve["requests"] == 12.0
        assert serve["swaps"] == 2.0
        assert serve["swap_rollbacks"] == 1.0
        assert serve["weights_version"] == 3.0
        resize = fresh.speed_monitor.resize_ledger()
        assert resize["resizes"] == 2
        assert resize["by_reason"]["preempt"] == 1
        assert resize["by_reason"]["relayout"] == 1
        assert resize["by_kind"]["relayout"] == pytest.approx(0.05)
        assert "restore" in resize["by_kind"]
        # No window survives the restart: the dead master cannot know when
        # the world re-formed, so only closed totals come back.
        assert resize["resize_open_s"] == 0.0
    finally:
        fresh.stop()


# -- trainer: cadence, shipping, and the injected flip ------------------------


class _DigestClient:
    def __init__(self):
        self.digests = []

    def report_digest(self, step, digest, check_every=0):
        self.digests.append((step, digest, check_every))

    def report_step(self, step, tokens=0, loss=0.0, anomalies=()):
        pass

    def report_telemetry(self, events, dropped=0):
        pass

    def report_event(self, event, detail=""):
        pass


def _tiny_trainer(client, sdc_check_every=2, fault_plan=""):
    from dlrover_tpu.trainer.elastic_trainer import (
        ElasticTrainer,
        TrainerConfig,
    )

    faults.configure(fault_plan, seed=5)
    cfg = gpt2_config(
        "124m", num_layers=1, d_model=64, num_heads=2,
        vocab_size=256, max_seq_len=16,
    )
    return ElasticTrainer(
        cfg,
        TrainerConfig(
            global_batch_size=16, seq_len=16, optimizer="sgd",
            learning_rate=1e-2, report_every=4,
            sdc_check_every=sdc_check_every,
        ),
        client=client,
        parallel=ParallelConfig(data=2, fsdp=4),
    )


def _run_trainer(client, steps=4, **kw):
    trainer = _tiny_trainer(client, **kw)
    try:
        rng = np.random.default_rng(0)
        for _ in range(steps):
            t = rng.integers(0, 256, size=(16, 17), dtype=np.int32)
            trainer.train_step({"inputs": t[:, :-1], "targets": t[:, 1:]})
        trainer._report(trainer._last_metrics)
        return trainer
    finally:
        trainer.close()
        faults.configure("")


def test_trainer_ships_digests_on_report_cadence():
    _needs_mesh()
    client = _DigestClient()
    trainer = _run_trainer(client, steps=4, sdc_check_every=2)
    assert [d[0] for d in client.digests] == [2, 4]
    assert all(len(d[1]) == 8 for d in client.digests)
    assert all(d[2] == 2 for d in client.digests)
    assert trainer._pending_digests == []  # the report drained them
    # Disabled path builds nothing.
    off = _DigestClient()
    t2 = _run_trainer(off, steps=2, sdc_check_every=0)
    assert off.digests == [] and t2._digest_fn is None


def test_trainer_injected_flip_diverges_digest():
    """Same model, same batches: the replica whose plan fires ``sdc.flip``
    reports a different digest at the flip step — the drill's detection
    signal, reproduced in-process."""
    _needs_mesh()
    clean = _run_trainer(_DigestClient(), steps=4, sdc_check_every=2)
    del clean
    clean_digests = _run_trainer(
        _DigestClient(), steps=4, sdc_check_every=2
    )
    client_a = _DigestClient()
    _run_trainer(client_a, steps=4, sdc_check_every=2)
    client_b = _DigestClient()
    _run_trainer(
        client_b, steps=4, sdc_check_every=2,
        fault_plan="sdc.flip:error@1",
    )
    del clean_digests
    # Uninjected reruns agree with each other...
    assert client_a.digests, "no digests shipped"
    # ...and the injected run diverges from the first check onward (the
    # flip persists in the live state, like real corruption).
    assert [d[0] for d in client_b.digests] == [d[0] for d in client_a.digests]
    assert client_b.digests[0][1] != client_a.digests[0][1]


def test_trainer_check_does_not_retrace():
    _needs_mesh()
    client = _DigestClient()
    trainer = _tiny_trainer(client, sdc_check_every=2)
    try:
        rng = np.random.default_rng(0)

        def step():
            t = rng.integers(0, 256, size=(16, 17), dtype=np.int32)
            trainer.train_step({"inputs": t[:, :-1], "targets": t[:, 1:]})

        step()
        step()  # first check pays the single digest compilation
        with trace_asserts.assert_no_retrace("train_step", "state_digest"):
            for _ in range(4):
                step()
        assert [s for s, _ in trainer._pending_digests] == [2, 4, 6]
    finally:
        trainer.close()
        faults.configure("")
