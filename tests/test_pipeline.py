"""Pipeline parallelism: loss parity with the non-pipelined stack and a
sharded end-to-end train step over a real pipe axis.

Mirrors the reference's pipeline tests (SURVEY.md §4 ``pipeline_test.py``,
498 LoC: multi-proc groups on one host, toy models, loss checks) on the
virtual CPU mesh.
"""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlrover_tpu.models.gpt2 import gpt2_config
from dlrover_tpu.models.transformer import TransformerLM
from dlrover_tpu.parallel import rules as lr
from dlrover_tpu.runtime.mesh import ParallelConfig, build_mesh
from dlrover_tpu.trainer import train_lib


def _tiny(pp=1, micro=0, **kw):
    return gpt2_config(
        "124m", num_layers=4, d_model=32, num_heads=4, vocab_size=128,
        max_seq_len=16, pipeline_stages=pp, num_microbatches=micro, **kw
    )


def _reshape_params_for_stages(params, stages):
    """pp=1 scanned params [L, ...] -> pipelined [S, L/S, ...] pytree."""
    blocks = params["blocks"]
    def reshape(leaf):
        return leaf.reshape(stages, leaf.shape[0] // stages, *leaf.shape[1:])
    piped = {
        "ticks": {"stages": {"layers": jax.tree.map(reshape, blocks)}}
    }
    out = dict(params)
    out["blocks"] = piped
    return out


@pytest.mark.slow  # forward-only path subsumed by the grads-match parity tests
def test_pipeline_matches_sequential_forward():
    cfg1 = _tiny(pp=1)
    cfg2 = _tiny(pp=2, micro=2)
    m1, m2 = TransformerLM(cfg1), TransformerLM(cfg2)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, 128)
    p1 = nn.meta.unbox(m1.init(jax.random.PRNGKey(0), tokens)["params"])
    p2 = _reshape_params_for_stages(p1, stages=2)
    # Structure must match what the pipelined model would itself create.
    ref = jax.tree.structure(
        nn.meta.unbox(m2.init(jax.random.PRNGKey(0), tokens)["params"])
    )
    assert jax.tree.structure(p2) == ref
    logits1, _ = m1.apply({"params": p1}, tokens)
    logits2, _ = m2.apply({"params": p2}, tokens)
    np.testing.assert_allclose(
        np.asarray(logits1), np.asarray(logits2), rtol=2e-3, atol=2e-3
    )


def test_pipeline_sharded_train_step_runs_and_matches_loss():
    """pp=2 x dp=2 x fsdp=2 on the 8-device CPU mesh: the full sharded train
    step must run and its first-step loss must match the pp=1 loss on the
    same params/batch."""
    devices = jax.devices()[:8]
    batch, seq = 8, 16
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, 128, size=(batch, seq + 1), dtype=np.int32)

    losses = {}
    for pp in (1, 2):
        cfg = _tiny(pp=pp, micro=4 if pp > 1 else 0, remat="full")
        model = TransformerLM(cfg)
        mesh = build_mesh(
            ParallelConfig(data=2, fsdp=2, pipe=pp, tensor=1),
            devices=devices[: 4 * pp],
        )
        train = train_lib.build_sharded_train(
            model, train_lib.make_optimizer("sgd", learning_rate=0.0),
            mesh, lr.DEFAULT_RULES,
            global_batch_size=batch, seq_len=seq,
        )
        if pp == 1:
            state = train.init(jax.random.PRNGKey(0))
            params1 = jax.tree.map(np.asarray, state.params)
        else:
            state = train.init(jax.random.PRNGKey(0))
            piped = _reshape_params_for_stages(params1, stages=2)
            state = state.replace(
                params=jax.tree.map(
                    lambda t, s: jax.device_put(t, s.sharding),
                    piped,
                    state.params,
                )
            )
        b = train_lib.shard_batch(
            {"inputs": tokens[:, :-1].copy(), "targets": tokens[:, 1:].copy()},
            train,
        )
        _, metrics = train.step(state, b)
        losses[pp] = float(metrics["loss"])
    assert np.isfinite(losses[2])
    np.testing.assert_allclose(losses[2], losses[1], rtol=2e-3)


@pytest.mark.slow  # second sequential reference compile, ~22s;
# test_pipeline_sharded_train_step_runs_and_matches_loss and the
# circular-interleave grads test stay as the tier-1 witnesses.
def test_pipeline_grads_match_sequential():
    """AD through the tick loop (the reverse-schedule backward) must produce
    the same gradients as the plain layer scan."""
    cfg1 = _tiny(pp=1)
    cfg2 = _tiny(pp=2, micro=2)
    m1, m2 = TransformerLM(cfg1), TransformerLM(cfg2)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, 128)
    targets = jax.random.randint(jax.random.PRNGKey(2), (4, 16), 0, 128)
    p1 = nn.meta.unbox(m1.init(jax.random.PRNGKey(0), tokens)["params"])
    p2 = _reshape_params_for_stages(p1, stages=2)

    def loss1(p):
        logits, _ = m1.apply({"params": p}, tokens)
        return train_lib.cross_entropy_loss(logits, targets)[0]

    def loss2(p):
        logits, _ = m2.apply({"params": p}, tokens)
        return train_lib.cross_entropy_loss(logits, targets)[0]

    g1 = jax.grad(loss1)(p1)
    g2 = jax.grad(loss2)(p2)
    g1_piped = _reshape_params_for_stages(g1, stages=2)
    flat1 = jax.tree.leaves(g1_piped)
    flat2 = jax.tree.leaves(g2)
    for a, b in zip(flat1, flat2):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-3, atol=5e-3
        )


def test_pipeline_validates_config():
    with pytest.raises(ValueError, match="divisible"):
        cfg = _tiny(pp=3)
        TransformerLM(cfg).init(
            jax.random.PRNGKey(0), jnp.zeros((4, 16), jnp.int32)
        )
    with pytest.raises(NotImplementedError, match="MoE"):
        cfg = gpt2_config(
            "124m", num_layers=4, d_model=32, num_heads=4, vocab_size=128,
            max_seq_len=16, pipeline_stages=2, num_experts=2,
        )
        TransformerLM(cfg).init(
            jax.random.PRNGKey(0), jnp.zeros((4, 16), jnp.int32)
        )


@pytest.mark.slow  # pp x tp build compiles a second mesh, ~12s on 1 core
def test_pipeline_composes_with_tensor_parallel():
    """pp=2 x tp=2 x dp=2 (the round-2 verdict's untested composition):
    loss parity with the unsharded pp=1 reference on the same params."""
    devices = jax.devices()[:8]
    batch, seq = 8, 16
    rng = np.random.default_rng(1)
    tokens = rng.integers(0, 128, size=(batch, seq + 1), dtype=np.int32)

    cfg1 = _tiny(pp=1, remat="full")
    model1 = TransformerLM(cfg1)
    mesh1 = build_mesh(ParallelConfig(data=-1), devices=devices[:1])
    train1 = train_lib.build_sharded_train(
        model1, train_lib.make_optimizer("sgd", learning_rate=0.0),
        mesh1, lr.DEFAULT_RULES, global_batch_size=batch, seq_len=seq,
    )
    state1 = train1.init(jax.random.PRNGKey(0))
    params1 = jax.tree.map(np.asarray, state1.params)
    b1 = train_lib.shard_batch(
        {"inputs": tokens[:, :-1].copy(), "targets": tokens[:, 1:].copy()},
        train1,
    )
    _, metrics1 = train1.step(state1, b1)

    cfg2 = _tiny(pp=2, micro=4, remat="full")
    model2 = TransformerLM(cfg2)
    mesh2 = build_mesh(
        ParallelConfig(data=2, pipe=2, tensor=2), devices=devices
    )
    train2 = train_lib.build_sharded_train(
        model2, train_lib.make_optimizer("sgd", learning_rate=0.0),
        mesh2, lr.DEFAULT_RULES, global_batch_size=batch, seq_len=seq,
    )
    state2 = train2.init(jax.random.PRNGKey(0))
    piped = _reshape_params_for_stages(params1, stages=2)
    state2 = state2.replace(
        params=jax.tree.map(
            lambda t, s: jax.device_put(t, s.sharding),
            piped, state2.params,
        )
    )
    b2 = train_lib.shard_batch(
        {"inputs": tokens[:, :-1].copy(), "targets": tokens[:, 1:].copy()},
        train2,
    )
    _, metrics2 = train2.step(state2, b2)
    np.testing.assert_allclose(
        float(metrics2["loss"]), float(metrics1["loss"]), rtol=2e-3
    )


def test_schedule_accounting_parity_and_interleaving_bounds():
    """tools/pipeline_account.py simulator invariants (VERDICT r3 #5):
    our schedule's bubble equals non-interleaved 1F1B; SPMD interleaving
    strictly loses; true interleaving's gain shrinks with M."""
    import sys, os
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    from tools.pipeline_account import (
        sim_1f1b,
        sim_1f1b_interleaved,
        sim_gpipe,
        sim_spmd,
    )

    for S, M in [(2, 4), (4, 8), (4, 32), (8, 16)]:
        ours = sim_spmd(S, M)
        ref = sim_1f1b(S, M)
        assert abs(ours["useful_fraction"] - ref["useful_fraction"]) < 1e-9
        assert abs(
            sim_gpipe(S, M)["useful_fraction"] - ref["useful_fraction"]
        ) < 1e-9
        # SPMD-style interleaving strictly loses
        assert sim_spmd(S, M, v=2)["useful_fraction"] < (
            ours["useful_fraction"]
        )
        # true interleaving wins, by less as M grows
        inter = sim_1f1b_interleaved(S, M, v=2)
        assert inter["useful_fraction"] > ref["useful_fraction"]
    gap_small_m = (
        sim_1f1b_interleaved(4, 8, 2)["useful_fraction"]
        - sim_1f1b(4, 8)["useful_fraction"]
    )
    gap_big_m = (
        sim_1f1b_interleaved(4, 32, 2)["useful_fraction"]
        - sim_1f1b(4, 32)["useful_fraction"]
    )
    assert gap_big_m < gap_small_m


@pytest.mark.slow  # forward-only check subsumed by the interleave grads-match test
def test_circular_interleave_matches_sequential_forward():
    """pipeline_interleave=2 (circular, interleaved-1F1B-equivalent
    schedule) computes the SAME function as the plain stack on the same
    stage-contiguous params (VERDICT r4 #4)."""
    cfg1 = _tiny(pp=1)
    cfgv = _tiny(pp=2, micro=4)
    cfgv = cfgv.__class__(**{**cfgv.__dict__, "pipeline_interleave": 2})
    m1, mv = TransformerLM(cfg1), TransformerLM(cfgv)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, 128)
    p1 = nn.meta.unbox(m1.init(jax.random.PRNGKey(0), tokens)["params"])
    pv = _reshape_params_for_stages(p1, stages=2)
    ref = jax.tree.structure(
        nn.meta.unbox(mv.init(jax.random.PRNGKey(0), tokens)["params"])
    )
    assert jax.tree.structure(pv) == ref  # checkpoint layout unchanged
    logits1, _ = m1.apply({"params": p1}, tokens)
    logitsv, _ = mv.apply({"params": pv}, tokens)
    np.testing.assert_allclose(
        np.asarray(logits1), np.asarray(logitsv), rtol=2e-3, atol=2e-3
    )


def test_circular_interleave_grads_match_sequential():
    cfg1 = _tiny(pp=1)
    cfgv = _tiny(pp=2, micro=2)
    cfgv = cfgv.__class__(**{**cfgv.__dict__, "pipeline_interleave": 2})
    m1, mv = TransformerLM(cfg1), TransformerLM(cfgv)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, 128)
    targets = jax.random.randint(jax.random.PRNGKey(2), (4, 16), 0, 128)
    p1 = nn.meta.unbox(m1.init(jax.random.PRNGKey(0), tokens)["params"])
    pv = _reshape_params_for_stages(p1, stages=2)

    def loss1(p):
        logits, _ = m1.apply({"params": p}, tokens)
        return train_lib.cross_entropy_loss(logits, targets)[0]

    def lossv(p):
        logits, _ = mv.apply({"params": p}, tokens)
        return train_lib.cross_entropy_loss(logits, targets)[0]

    g1 = _reshape_params_for_stages(jax.grad(loss1)(p1), stages=2)
    gv = jax.grad(lossv)(pv)
    for a, b in zip(jax.tree.leaves(g1), jax.tree.leaves(gv)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-3, atol=5e-3
        )


@pytest.mark.slow  # sharded circular-interleave build, ~15s on 1 core
def test_circular_interleave_sharded_train_step():
    """pp=2 x dp=2 x v=2 over the virtual mesh: the sharded train step
    runs and first-step loss matches pp=1."""
    devices = jax.devices()[:4]
    batch, seq = 8, 16
    rng = np.random.default_rng(3)
    tokens = rng.integers(0, 128, size=(batch, seq + 1), dtype=np.int32)

    cfg1 = _tiny(pp=1, remat="full")
    model1 = TransformerLM(cfg1)
    mesh1 = build_mesh(ParallelConfig(data=-1), devices=devices[:1])
    train1 = train_lib.build_sharded_train(
        model1, train_lib.make_optimizer("sgd", learning_rate=0.0),
        mesh1, lr.DEFAULT_RULES, global_batch_size=batch, seq_len=seq,
    )
    state1 = train1.init(jax.random.PRNGKey(0))
    params1 = jax.tree.map(np.asarray, state1.params)
    b1 = train_lib.shard_batch(
        {"inputs": tokens[:, :-1].copy(), "targets": tokens[:, 1:].copy()},
        train1,
    )
    _, metrics1 = train1.step(state1, b1)

    cfgv = _tiny(pp=2, micro=4, remat="full")
    cfgv = cfgv.__class__(**{**cfgv.__dict__, "pipeline_interleave": 2})
    modelv = TransformerLM(cfgv)
    meshv = build_mesh(ParallelConfig(data=2, pipe=2), devices=devices)
    trainv = train_lib.build_sharded_train(
        modelv, train_lib.make_optimizer("sgd", learning_rate=0.0),
        meshv, lr.DEFAULT_RULES, global_batch_size=batch, seq_len=seq,
    )
    statev = trainv.init(jax.random.PRNGKey(0))
    piped = _reshape_params_for_stages(params1, stages=2)
    statev = statev.replace(
        params=jax.tree.map(
            lambda t, s: jax.device_put(t, s.sharding),
            piped, statev.params,
        )
    )
    bv = train_lib.shard_batch(
        {"inputs": tokens[:, :-1].copy(), "targets": tokens[:, 1:].copy()},
        trainv,
    )
    _, metricsv = trainv.step(statev, bv)
    np.testing.assert_allclose(
        float(metricsv["loss"]), float(metrics1["loss"]), rtol=2e-3
    )


def test_circular_interleave_validates_config():
    with pytest.raises(ValueError, match="microbatches >= stages"):
        _tiny(pp=2, micro=1).__class__(
            **{**_tiny(pp=2, micro=1).__dict__, "pipeline_interleave": 2}
        )
    with pytest.raises(ValueError, match="stages\\*interleave"):
        _tiny(pp=2, micro=4).__class__(
            **{**_tiny(pp=2, micro=4).__dict__, "pipeline_interleave": 3}
        )
