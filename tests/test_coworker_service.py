"""Cross-host coworker data service (VERDICT r3 #7).

Ref ``atorch/atorch/service/coworker_data_service.py`` +
``protos/coworker.proto``: preprocessing host serves collated batches over
gRPC; training hosts consume with exactly-once delivery.  The "two virtual
hosts" here are a server subprocess (the coworker host) and two consumer
iterators in the test process (two trainer hosts).
"""

import multiprocessing as mp
import sys
import time

import numpy as np
import pytest

from dlrover_tpu.data.coworker_service import (
    CoworkerDataServer,
    RemoteBatchIterator,
    decode_batch,
    encode_batch,
)


def _batches(n, rows=4):
    for i in range(n):
        yield {
            "inputs": np.full((rows, 8), i, np.int32),
            "weights": np.ones((rows,), np.float32) * i,
        }


def test_encode_decode_roundtrip():
    batch = {
        "a": np.arange(12, dtype=np.int64).reshape(3, 4),
        "b": np.random.default_rng(0).normal(size=(2, 2)).astype(np.float32),
        "scalar": np.asarray(7, np.int32),
    }
    out = decode_batch(encode_batch(5, batch))
    assert set(out) == set(batch)
    for key in batch:
        np.testing.assert_array_equal(out[key], batch[key])


def test_remote_iterator_streams_in_order_and_ends():
    server = CoworkerDataServer(_batches(6), port=0)
    try:
        it = RemoteBatchIterator(f"localhost:{server.port}", consumer="t0")
        got = [b["inputs"][0, 0] for b in it]
        assert got == list(range(6))
        it.close()
    finally:
        server.close()


def test_two_consumers_share_exactly_once():
    server = CoworkerDataServer(_batches(10), port=0)
    try:
        a = RemoteBatchIterator(f"localhost:{server.port}", consumer="a")
        b = RemoteBatchIterator(f"localhost:{server.port}", consumer="b")
        seen = []
        ita, itb = iter(a), iter(b)
        done_a = done_b = False
        while not (done_a and done_b):
            if not done_a:
                try:
                    seen.append(int(next(ita)["inputs"][0, 0]))
                except StopIteration:
                    done_a = True
            if not done_b:
                try:
                    seen.append(int(next(itb)["inputs"][0, 0]))
                except StopIteration:
                    done_b = True
        assert sorted(seen) == list(range(10))  # exactly once, split across
        a.close()
        b.close()
    finally:
        server.close()


def test_producer_error_propagates():
    def bad():
        yield {"x": np.zeros((2,), np.float32)}
        raise ValueError("tokenizer exploded")

    server = CoworkerDataServer(bad(), port=0)
    try:
        it = RemoteBatchIterator(f"localhost:{server.port}")
        stream = iter(it)
        next(stream)  # first batch ok
        with pytest.raises(RuntimeError, match="tokenizer exploded"):
            next(stream)
        it.close()
    finally:
        server.close()


def _serve_proc(port_q, n):
    # The coworker "host": its own process with its own server + loader.
    from dlrover_tpu.data.coworker import CoworkerDataLoader
    from dlrover_tpu.data.coworker_service import CoworkerDataServer

    def sample_fn(i):
        return {"inputs": np.full((8,), i, np.int32)}

    loader = CoworkerDataLoader(
        sample_fn, batch_size=4, num_workers=2,
        source=iter(range(n * 4)), slot_bytes=1 << 20,
    )
    server = CoworkerDataServer(iter(loader), port=0)
    port_q.put(server.port)
    # Serve until the stream is drained (end sentinel stays in the outbox).
    time.sleep(8)
    server.close()
    loader.close()


@pytest.mark.slow  # forks a coworker-host process that serves for ~8s
def test_cross_process_host_with_shm_ring():
    """Full stack across a process boundary: coworker host process runs
    preprocessing workers + shm ring + server; this process consumes."""
    ctx = mp.get_context("spawn" if sys.platform == "darwin" else "fork")
    port_q = ctx.Queue()
    n_batches = 5
    proc = ctx.Process(target=_serve_proc, args=(port_q, n_batches))
    proc.start()
    try:
        port = port_q.get(timeout=10)
        it = RemoteBatchIterator(f"localhost:{port}", consumer="trainer0")
        got = []
        for batch in it:
            # each preprocessed batch is 4 consecutive indices
            got.extend(batch["inputs"][:, 0].tolist())
        assert sorted(got) == list(range(n_batches * 4))
        it.close()
    finally:
        proc.join(timeout=15)
        if proc.is_alive():
            proc.terminate()
