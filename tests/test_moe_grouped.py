"""Dropless grouped-GEMM MoE dispatch: parity with the capacity einsum path
and the no-drop guarantee (round-2 verdict: wire grouped_matmul into MoEMlp).
"""

import jax
import jax.numpy as jnp
import numpy as np

from dlrover_tpu.models.moe import MoEMlp


def _run(dispatch, x, capacity_factor=8.0, seed=0):
    layer = MoEMlp(
        num_experts=4,
        d_ff=64,
        top_k=2,
        capacity_factor=capacity_factor,
        activation="gelu",
        dtype=jnp.float32,
        param_dtype=jnp.float32,
        dispatch=dispatch,
        gmm_block_rows=8,
    )
    params = layer.init(jax.random.PRNGKey(seed), x)
    out, aux = layer.apply(params, x)
    return np.asarray(out), float(aux), params


def test_grouped_matches_einsum_when_capacity_ample():
    """With capacity large enough that the einsum path drops nothing, both
    dispatch implementations compute the same function."""
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(2, 16, 32)), jnp.float32)
    layer_kw = dict(seed=0)
    out_e, aux_e, params = _run("einsum", x, **layer_kw)
    # Same params: re-apply with grouped dispatch.
    layer_g = MoEMlp(
        num_experts=4, d_ff=64, top_k=2, capacity_factor=8.0,
        activation="gelu", dtype=jnp.float32, param_dtype=jnp.float32,
        dispatch="grouped", gmm_block_rows=8,
    )
    out_g, aux_g = layer_g.apply(params, x)
    np.testing.assert_allclose(out_e, np.asarray(out_g), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(aux_e, float(aux_g), rtol=1e-5)


def test_grouped_is_dropless_under_tight_capacity():
    """capacity_factor only affects the einsum path: grouped keeps every
    token-choice even when the einsum path would drop most of them."""
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(2, 32, 32)), jnp.float32)
    out_ample, _, params = _run("einsum", x, capacity_factor=8.0, seed=1)
    layer_g = MoEMlp(
        num_experts=4, d_ff=64, top_k=2, capacity_factor=0.25,
        activation="gelu", dtype=jnp.float32, param_dtype=jnp.float32,
        dispatch="grouped", gmm_block_rows=8,
    )
    out_g, _ = layer_g.apply(params, x)
    # Grouped output equals the no-drop function regardless of capacity.
    np.testing.assert_allclose(
        out_ample, np.asarray(out_g), rtol=1e-4, atol=1e-5
    )


def test_grouped_gradients_flow():
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.normal(size=(2, 16, 32)), jnp.float32)
    layer = MoEMlp(
        num_experts=4, d_ff=64, top_k=2, activation="swiglu",
        dtype=jnp.float32, param_dtype=jnp.float32,
        dispatch="grouped", gmm_block_rows=8,
    )
    params = layer.init(jax.random.PRNGKey(2), x)

    def loss_fn(p):
        out, aux = layer.apply(p, x)
        return jnp.sum(out ** 2) + aux

    grads = jax.grad(loss_fn)(params)
    norms = [float(jnp.linalg.norm(g)) for g in jax.tree.leaves(grads)]
    assert all(np.isfinite(norms))
    assert any(n > 0 for n in norms), "no gradient reached the experts"
