"""Coworker multiprocess preprocessing loader (shm batch transport)."""

import numpy as np
import pytest

from dlrover_tpu.data.coworker import CoworkerDataLoader
from dlrover_tpu.data.loader import (
    ElasticDistributedSampler,
    synthetic_lm_sample_fn,
)


@pytest.mark.slow  # spawns worker subprocesses; stall-timeout bound when loaded
def test_coworker_matches_inprocess_batches():
    """Worker-process preprocessing must produce byte-identical, in-order
    batches to calling sample_fn inline."""
    sample_fn = synthetic_lm_sample_fn(vocab_size=97, seq_len=12, seed=3)
    loader = CoworkerDataLoader(
        sample_fn, batch_size=4, num_workers=2, slot_bytes=1 << 20
    )
    try:
        it = iter(loader)
        got = [next(it) for _ in range(5)]
    finally:
        loader.close()
    for b, batch in enumerate(got):
        expected = {
            key: np.stack(
                [sample_fn(b * 4 + i)[key] for i in range(4)]
            )
            for key in ("inputs", "targets")
        }
        for key in expected:
            np.testing.assert_array_equal(batch[key], expected[key])


def test_coworker_finite_sampler_drains_and_stops():
    sampler = ElasticDistributedSampler(
        dataset_size=24, num_replicas=1, rank=0, shuffle=False
    )
    sample_fn = synthetic_lm_sample_fn(vocab_size=31, seq_len=4)
    loader = CoworkerDataLoader(
        sample_fn, batch_size=6, num_workers=2, slot_bytes=1 << 18
    )
    try:
        loader.source = sampler
        batches = list(loader)
    finally:
        loader.close()
    assert len(batches) == 4
    # In-order delivery: first batch holds indices 0..5.
    np.testing.assert_array_equal(
        batches[0]["inputs"][0], sample_fn(0)["inputs"]
    )


def test_coworker_oversized_batch_raises_cleanly():
    sample_fn = synthetic_lm_sample_fn(vocab_size=31, seq_len=4096)
    loader = CoworkerDataLoader(
        sample_fn, batch_size=64, num_workers=1, slot_bytes=1 << 12
    )
    try:
        with pytest.raises(RuntimeError, match="coworker"):
            next(iter(loader))
    finally:
        loader.close()


def test_coworker_sample_error_surfaces_with_surviving_workers():
    """One worker hitting a bad sample must raise promptly even while other
    workers stay alive (a lost seq would stall in-order delivery)."""

    def flaky(index):
        if index == 5:
            raise ValueError("bad record")
        return {"x": np.full((4,), index, np.int32)}

    loader = CoworkerDataLoader(
        flaky, batch_size=2, num_workers=2, slot_bytes=1 << 16
    )
    try:
        with pytest.raises(RuntimeError, match="coworker"):
            for _ in iter(loader):
                pass
    finally:
        loader.close()


class _HangingSample:
    """Picklable sample_fn that never returns (wedged-worker simulator)."""

    def __call__(self, index):
        import time

        time.sleep(3600)


@pytest.mark.slow  # waits out the stall watchdog, ~8s on 1 core
def test_stalled_pipeline_raises_instead_of_hanging():
    """Live-but-wedged workers (e.g. a forked child deadlocked on an
    inherited lock) must surface as an error, never an infinite hang —
    the agent restarts a crashed trainer; nothing rescues a hung one."""
    loader = CoworkerDataLoader(
        _HangingSample(), batch_size=2, num_workers=1,
        slot_bytes=1 << 16, stall_timeout_s=3.0,
    )
    try:
        with pytest.raises(RuntimeError, match="stalled"):
            next(iter(loader))
    finally:
        loader.close()


def test_unpicklable_sample_fn_falls_back_to_fork():
    captured = {}
    local = 3

    def closure_fn(index):
        return {"x": np.full((2,), index + local, np.int32)}

    loader = CoworkerDataLoader(
        closure_fn, batch_size=2, num_workers=1, slot_bytes=1 << 16
    )
    assert loader.start_method == "fork"
    try:
        batch = next(iter(loader))
        np.testing.assert_array_equal(batch["x"][0], [3, 3])
    finally:
        loader.close()


def test_picklable_sample_fn_uses_spawn():
    loader = CoworkerDataLoader(
        synthetic_lm_sample_fn(vocab_size=7, seq_len=4),
        batch_size=2, num_workers=1, slot_bytes=1 << 16,
    )
    assert loader.start_method == "spawn"
    loader.close()
