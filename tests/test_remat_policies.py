"""Remat-policy tests: registry, parity across all policies, offload
fallback, and recompute elision.

The round-4 perf work (PROFILE.md) saves the flash kernel's own outputs
(o, lse) as named remat targets so the backward replay drops the attention
forward recompute; the remat-policy subsystem (ops/remat_policy.py)
generalizes that into named, composable policies with host offload.
These tests pin down (a) gradient equivalence across every registered
policy, (b) the save-only fallback on backends without pinned host
memory, and (c) that the named saveables actually exist in the jaxpr.
"""

from __future__ import annotations

import functools
import logging

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlrover_tpu.models.gpt2 import gpt2_config
from dlrover_tpu.models.transformer import TransformerConfig, TransformerLM
from dlrover_tpu.ops import remat_policy as rp


def _tiny(remat: str, impl: str = "flash"):
    cfg = gpt2_config(
        "124m", num_layers=2, d_model=64, num_heads=2, vocab_size=128,
        max_seq_len=64, param_dtype=jnp.float32,
        remat=remat, attention_impl=impl,
        flash_block_q=32, flash_block_kv=32,
    )
    return TransformerLM(cfg), cfg


@functools.lru_cache(maxsize=None)
def _loss_and_grads(remat: str, impl: str = "flash"):
    # Cached: the parametrized parity sweep reuses the "none" reference
    # (and the fallback test reuses "offload") instead of re-tracing the
    # same jit per test — each trace is seconds of CPU compile time.
    model, cfg = _tiny(remat, impl)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 64), 0,
                                cfg.vocab_size)
    params = model.init(jax.random.PRNGKey(0), tokens)

    def loss(p):
        logits, aux = model.apply(p, tokens)
        return jnp.mean(logits.astype(jnp.float32) ** 2) + aux

    l, g = jax.jit(jax.value_and_grad(loss))(params)
    return l, g


@pytest.mark.parametrize("remat", ["flash_only", "flash_res"])
def test_flash_policies_match_attn_out_grads(remat):
    l_ref, g_ref = _loss_and_grads("attn_out")
    l, g = _loss_and_grads(remat)
    np.testing.assert_allclose(float(l), float(l_ref), rtol=1e-5)
    flat_ref = jax.tree_util.tree_leaves(g_ref)
    flat = jax.tree_util.tree_leaves(g)
    for a, b in zip(flat, flat_ref):
        np.testing.assert_allclose(
            np.asarray(a, np.float64), np.asarray(b, np.float64),
            rtol=2e-4, atol=2e-6,
        )


_ALL_POLICIES = sorted(rp.available()) + ["offload:attn_out,mlp_wo"]


@pytest.mark.parametrize(
    "remat",
    [
        # flash_only recompiles the Pallas kernel in the bwd pass (~12s on
        # 1 core) and is already graded against attn_out grads below;
        # flash_res (~16s) likewise — attn_out stays the tier-1 witness
        # here.
        pytest.param(p, marks=pytest.mark.slow)
        if p in ("flash_only", "flash_res") else p
        for p in _ALL_POLICIES
    ],
)
def test_every_registered_policy_matches_none_grads(remat):
    """Loss/grad parity for EVERY policy the registry knows (plus a
    selective offload list) against the no-remat baseline — the same
    harness as the pipeline parity tests, rtol 2e-3.

    Non-flash policies run under xla attention (the interpreted flash
    kernel dominates CPU compile time and adds nothing to a remat parity
    check); flash-name policies need the flash kernel's named residuals.
    """
    impl = "flash" if rp.resolve(remat).requires_flash else "xla"
    l_ref, g_ref = _loss_and_grads("none", impl)
    l, g = _loss_and_grads(remat, impl)
    np.testing.assert_allclose(float(l), float(l_ref), rtol=2e-3)
    for a, b in zip(
        jax.tree_util.tree_leaves(g), jax.tree_util.tree_leaves(g_ref)
    ):
        np.testing.assert_allclose(
            np.asarray(a, np.float64), np.asarray(b, np.float64),
            rtol=2e-3, atol=1e-5,
        )


def test_registry_resolves_and_canonicalizes():
    # Selective lists canonicalize to a stable order...
    assert rp.resolve("offload:mlp_wo,qkv_proj").name == (
        "offload:qkv_proj,mlp_wo"
    )
    # ...and the default name set folds back to the plain alias.
    assert rp.resolve("offload:mlp_wo,attn_out,qkv_proj").name == "offload"
    offload = rp.resolve("offload")
    assert offload.offload_names == ("qkv_proj", "attn_out", "mlp_wo")
    assert offload.recompute_fraction == 0.0
    assert offload.offload_bytes_per_token_layer == 5.0
    with pytest.raises(ValueError, match="unknown offload target"):
        rp.resolve("offload:nonsense")
    with pytest.raises(ValueError, match="remat must be one of"):
        rp.resolve("bogus_policy")
    # Flash-name policies are rejected under non-flash impls, selective
    # offload lists included.
    with pytest.raises(ValueError, match="attention_impl='flash'"):
        rp.validate("offload:flash_out", attention_impl="xla")
    with pytest.raises(ValueError, match="attention_impl='flash'"):
        TransformerConfig(remat="flash_only", attention_impl="xla")


def test_config_accepts_selective_offload_strings():
    cfg = gpt2_config("124m", num_layers=2, remat="offload:attn_out,mlp_wo")
    assert cfg.remat == "offload:attn_out,mlp_wo"
    with pytest.raises(ValueError, match="remat must be one of"):
        gpt2_config("124m", remat="offlaod")


def test_offload_falls_back_to_save_only_without_pinned_host(monkeypatch):
    """Satellite: on a backend with no pinned_host memory kind the offload
    policy must degrade to the save-only equivalent with a logged warning
    — not crash (CPU test meshes are exactly this backend)."""
    monkeypatch.setattr(rp, "host_offload_supported", lambda device=None: False)
    rp._fallback_warned.clear()
    records = []

    class _Capture(logging.Handler):
        def emit(self, record):
            records.append(record.getMessage())

    handler = _Capture()
    logging.getLogger("dlrover_tpu").addHandler(handler)
    try:
        policy = rp.jax_policy("offload")
    finally:
        logging.getLogger("dlrover_tpu").removeHandler(handler)
    assert policy is not None
    assert any("pinned_host" in m and "save-only" in m for m in records)
    # The degraded policy is the save-only twin: grads match a policy that
    # saves the same names in HBM.
    l_off, g_off = _loss_and_grads("offload", "xla")
    l_ref, g_ref = _loss_and_grads("none", "xla")
    np.testing.assert_allclose(float(l_off), float(l_ref), rtol=2e-3)
    for a, b in zip(
        jax.tree_util.tree_leaves(g_off), jax.tree_util.tree_leaves(g_ref)
    ):
        np.testing.assert_allclose(
            np.asarray(a, np.float64), np.asarray(b, np.float64),
            rtol=2e-3, atol=1e-5,
        )
    # Warned once, not per trace.
    rp._fallback_warned.clear()
    records.clear()
    logging.getLogger("dlrover_tpu").addHandler(handler)
    try:
        rp.jax_policy("offload")
        rp.jax_policy("offload")
    finally:
        logging.getLogger("dlrover_tpu").removeHandler(handler)
    assert len([m for m in records if "falling" in m or "save-only" in m]) == 1


def test_named_saveables_present_in_jaxpr():
    """qkv_proj / attn_out / mlp_out / mlp_wo must be tagged in the traced
    program — otherwise offload/selective policies silently save nothing."""
    model, cfg = _tiny("offload", "xla")
    tokens = jnp.zeros((2, 64), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens)

    def loss(p):
        logits, aux = model.apply(p, tokens)
        return jnp.mean(logits.astype(jnp.float32) ** 2) + aux

    txt = str(jax.make_jaxpr(jax.grad(loss))(params))
    for name in ("qkv_proj", "attn_out", "mlp_out", "mlp_wo"):
        assert name in txt, f"checkpoint_name {name!r} missing from jaxpr"


def test_flash_res_names_present_in_jaxpr():
    """The custom_vjp fwd rule must emit the named saveables the policy keys
    on — if someone renames them the policy silently degrades to 'full'."""
    model, cfg = _tiny("flash_res")
    tokens = jnp.zeros((2, 64), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens)

    def loss(p):
        logits, aux = model.apply(p, tokens)
        return jnp.mean(logits.astype(jnp.float32) ** 2) + aux

    jaxpr = jax.make_jaxpr(jax.grad(loss))(params)
    txt = str(jaxpr)
    assert "flash_out" in txt and "flash_lse" in txt
