"""Remat-policy tests: flash_only/flash_res numerics + recompute elision.

The round-4 perf work (PROFILE.md) saves the flash kernel's own outputs
(o, lse) as named remat targets so the backward replay drops the attention
forward recompute.  These tests pin down (a) gradient equivalence across
policies and (b) that the saved-name mechanism actually elides the forward
kernel from the backward scan body.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlrover_tpu.models.gpt2 import gpt2_config
from dlrover_tpu.models.transformer import TransformerLM


def _tiny(remat: str):
    cfg = gpt2_config(
        "124m", num_layers=2, d_model=64, num_heads=2, vocab_size=128,
        max_seq_len=64, param_dtype=jnp.float32,
        remat=remat, attention_impl="flash",
        flash_block_q=32, flash_block_kv=32,
    )
    return TransformerLM(cfg), cfg


def _loss_and_grads(remat: str):
    model, cfg = _tiny(remat)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 64), 0,
                                cfg.vocab_size)
    params = model.init(jax.random.PRNGKey(0), tokens)

    def loss(p):
        logits, aux = model.apply(p, tokens)
        return jnp.mean(logits.astype(jnp.float32) ** 2) + aux

    l, g = jax.jit(jax.value_and_grad(loss))(params)
    return l, g


@pytest.mark.parametrize("remat", ["flash_only", "flash_res"])
def test_flash_policies_match_attn_out_grads(remat):
    l_ref, g_ref = _loss_and_grads("attn_out")
    l, g = _loss_and_grads(remat)
    np.testing.assert_allclose(float(l), float(l_ref), rtol=1e-5)
    flat_ref = jax.tree_util.tree_leaves(g_ref)
    flat = jax.tree_util.tree_leaves(g)
    for a, b in zip(flat, flat_ref):
        np.testing.assert_allclose(
            np.asarray(a, np.float64), np.asarray(b, np.float64),
            rtol=2e-4, atol=2e-6,
        )


def test_flash_res_names_present_in_jaxpr():
    """The custom_vjp fwd rule must emit the named saveables the policy keys
    on — if someone renames them the policy silently degrades to 'full'."""
    model, cfg = _tiny("flash_res")
    tokens = jnp.zeros((2, 64), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens)

    def loss(p):
        logits, aux = model.apply(p, tokens)
        return jnp.mean(logits.astype(jnp.float32) ** 2) + aux

    jaxpr = jax.make_jaxpr(jax.grad(loss))(params)
    txt = str(jaxpr)
    assert "flash_out" in txt and "flash_lse" in txt
