"""auto_tune strategy search on the virtual 8-device CPU mesh.

Mirrors the reference's auto_accelerate tests
(ref ``atorch/atorch/tests/common_tests/auto_accelerate_test.py``): the
search must produce a feasible, runnable strategy without hand-picking.
"""

import jax
import pytest

from dlrover_tpu.auto import auto_tune
from dlrover_tpu.auto.tune import enumerate_candidates
from dlrover_tpu.models.gpt2 import gpt2_config
from dlrover_tpu.models.llama import moe_llama_config


def tiny_cfg(**kw):
    return gpt2_config(
        "124m", num_layers=2, d_model=64, num_heads=4,
        vocab_size=512, max_seq_len=64, **kw,
    )


def test_enumeration_respects_divisibility():
    cands = enumerate_candidates(tiny_cfg(), 8)
    assert cands
    for c in cands:
        sizes = c.parallel.sizes(8)
        assert sizes["tensor"] in (1, 2, 4)  # must divide 4 heads
        if c.parallel.seq > 1:
            assert 4 % (c.parallel.seq * c.parallel.tensor) == 0
        assert c.parallel.expert == 1  # dense model: no ep
        if c.parallel.pipe > 1:
            assert 2 % c.parallel.pipe == 0


def test_enumeration_moe_gets_expert_axis():
    cfg = moe_llama_config(
        "tiny", num_experts=2, num_layers=2, vocab_size=512, max_seq_len=64
    )
    cands = enumerate_candidates(cfg, 8)
    assert any(c.parallel.expert == 2 for c in cands)
    # MoE pipeline is unsupported (pipeline.py guard): never enumerated.
    assert all(c.parallel.pipe == 1 for c in cands)


@pytest.mark.slow  # compiles every candidate strategy, ~13s on 1 core
def test_auto_tune_picks_runnable_strategy():
    n = min(8, len(jax.devices()))
    result = auto_tune(
        tiny_cfg(),
        global_batch_size=16,
        n_devices=n,
        optimizer="adamw",
        max_measure=2,
    )
    assert result.parallel.sizes(n)  # multiplies to n
    assert result.best.measured_step_time is not None
    assert result.model_config.remat == result.remat
    # Ranked record doubles as the strategy report (dryrun evidence).
    assert result.candidates[0].est_step_time > 0


def test_auto_tune_memory_pruning_rejects_oversized():
    """A model far beyond HBM at dp=1 must push the search toward sharded
    strategies or fail loudly — never silently pick an OOM config."""
    big = gpt2_config("1.5b", max_seq_len=1024)
    cands = enumerate_candidates(big, 8, remat_policies=("none",))
    from dlrover_tpu.auto.tune import _estimate

    dp_only = [
        c for c in cands
        if c.parallel.data == 8 and c.parallel.fsdp == 1
    ]
    assert dp_only
    # On CPU specs (8 GB budget in the model table) a 1.5B adamw state
    # with remat=none cannot fit a single device's share.
    _estimate(dp_only[0], big, 64, 1024, "adamw", 8)
    assert dp_only[0].rejected


@pytest.mark.slow  # compiles one program per batch multiple, ~22s on 1 core
def test_auto_tune_batch_search_opt_in():
    """search_batch explores batch multiples, ranks by throughput, and
    reports the winner's batch; default search leaves batch untouched."""
    n = min(8, len(jax.devices()))
    result = auto_tune(
        tiny_cfg(),
        global_batch_size=16,
        n_devices=n,
        optimizer="adamw",
        max_measure=2,
        search_batch=True,
    )
    assert result.global_batch_size in (16, 32, 64)
    assert result.best.measured_tokens_per_sec is not None
    # Default path keeps the sentinel (caller's batch stands).
    plain = auto_tune(
        tiny_cfg(), global_batch_size=16, n_devices=n, measure=False,
    )
    assert plain.global_batch_size == 0


def test_search_kernels_widens_space_and_estimates():
    """VERDICT r3 #9: flash blocks / CE chunking / microbatches /
    quantized-DCN knobs enter the search (estimate-ranked, no measure)."""
    from dlrover_tpu.auto import tune

    cfg = gpt2_config(
        "124m", num_layers=2, d_model=64, num_heads=4, vocab_size=512,
        max_seq_len=512, attention_impl="flash",
    )
    narrow = tune.enumerate_candidates(cfg, 8, seq_len=512)
    wide = tune.enumerate_candidates(
        cfg, 8, search_kernels=True, seq_len=512, multihost=True,
    )
    assert len(wide) > 4 * len(narrow)
    # every knob dimension is represented
    assert any(c.flash_block != (0, 0) for c in wide)
    assert any(c.ce_chunks == 16 for c in wide)
    assert any(c.quantized_dcn for c in wide)
    pipes = [c for c in wide if c.parallel.pipe > 1]
    if pipes:
        assert any(c.microbatches > c.parallel.pipe for c in pipes)

    result = tune.auto_tune(
        cfg, global_batch_size=16, seq_len=512, n_devices=8,
        measure=False, search_kernels=True,
    )
    assert result.best.est_step_time != float("inf")
    # the winner's knobs surface on the result
    assert result.ce_chunks == result.best.ce_chunks
    if result.best.flash_block != (0, 0):
        assert result.model_config.flash_block_q == result.best.flash_block[0]


def test_sampled_search_with_refinement_is_deterministic():
    from dlrover_tpu.auto import tune

    cfg = gpt2_config(
        "124m", num_layers=2, d_model=64, num_heads=4, vocab_size=512,
        max_seq_len=512, attention_impl="flash",
    )
    kwargs = dict(
        global_batch_size=16, seq_len=512, n_devices=8, measure=False,
        search_kernels=True, max_enumerate=64,
    )
    a = tune.auto_tune(cfg, **kwargs)
    b = tune.auto_tune(cfg, **kwargs)
    assert tune._cand_key(a.best) == tune._cand_key(b.best)
    assert len([c for c in a.candidates if not c.rejected]) > 0


def test_unchunked_ce_memory_includes_logits():
    """CE chunking's real effect is the logits working set: the estimator
    must see it (it is what OOMs the 1.5B bench without chunking)."""
    from dlrover_tpu.auto import tune
    from dlrover_tpu.runtime.mesh import ParallelConfig

    cfg = gpt2_config(
        "124m", num_layers=2, d_model=64, num_heads=4, vocab_size=50304,
        max_seq_len=512,
    )
    plain = tune.Candidate(ParallelConfig(data=8), "attn_out")
    chunked = tune.Candidate(
        ParallelConfig(data=8), "attn_out", ce_chunks=16
    )
    for cand in (plain, chunked):
        tune._estimate(cand, cfg, 64, 512, "adamw", 8)
    assert plain.est_hbm_gb > chunked.est_hbm_gb


def test_interleave_knob_enumerated_and_materialized():
    """pipeline_interleave joins the searched knobs (r5: the circular
    schedule is a real capability, so auto_tune must be able to pick
    it); the winner's v lands on the tuned model config."""
    config = gpt2_config(
        "124m", num_layers=4, d_model=64, num_heads=4, vocab_size=256,
        max_seq_len=64,
    )
    cands = enumerate_candidates(
        config, 4, search_kernels=True, seq_len=64,
    )
    piped = [c for c in cands if c.parallel.pipe == 2]
    assert any(c.interleave == 2 for c in piped)
    assert any(c.interleave == 0 for c in piped)
    # layers=6 cannot split into 2*2 chunks with pipe=2? 6 % 4 != 0 ->
    # no v=2 candidates for that pipe depth.
    config6 = gpt2_config(
        "124m", num_layers=6, d_model=64, num_heads=4, vocab_size=256,
        max_seq_len=64,
    )
    cands6 = enumerate_candidates(config6, 4, search_kernels=True,
                                  seq_len=64)
    assert not any(
        c.interleave == 2 for c in cands6 if c.parallel.pipe == 2
    )

    import dataclasses

    from dlrover_tpu.auto.tune import _estimate

    a = next(c for c in piped if c.interleave == 0 and c.microbatches == 2
             and c.remat == "full" and c.ce_chunks == 0
             and c.flash_block == (0, 0))
    b = dataclasses.replace(a, interleave=2)
    for c in (a, b):
        _estimate(c, config, 8, 64, "adamw", 4)
    assert b.est_step_time != a.est_step_time  # the knob changes the model


# ---- remat-policy accounting (ops/remat_policy.py tentpole) -------------

_V5E_SPECS = (197e12, 819e9, 16e9, 4.5e10)


def _offload_vs_flash(monkeypatch, dma_bw):
    """Estimate offload vs flash_only at bench-like compute-bound shapes
    with the chip pinned to v5e and a controlled host-DMA bandwidth."""
    from dlrover_tpu.auto import tune
    from dlrover_tpu.runtime.mesh import ParallelConfig

    monkeypatch.setattr(tune, "chip_specs", lambda device=None: _V5E_SPECS)
    monkeypatch.setattr(
        tune, "host_dma_bandwidth", lambda device=None: dma_bw
    )
    cfg = gpt2_config("1.5b", max_seq_len=1024, attention_impl="flash")
    off = tune.Candidate(ParallelConfig(fsdp=8), "offload")
    fla = tune.Candidate(ParallelConfig(fsdp=8), "flash_only")
    for cand in (off, fla):
        tune._estimate(cand, cfg, 16, 1024, "adamw", 8)
        assert not cand.rejected, cand.rejected
    return off, fla


def test_offload_beats_flash_only_iff_dma_cheaper(monkeypatch):
    """Acceptance: the ranking flips exactly with the modeled trade —
    offload outranks flash_only iff its DMA time is below the recompute
    time flash_only pays.  Both regimes, same shapes, only the host link
    speed differs."""
    # Fast host link (NVLink-class): DMA ~free, offload must win.
    off, fla = _offload_vs_flash(monkeypatch, dma_bw=1e12)
    assert off.est_dma_time < fla.est_recompute_time
    assert off.est_step_time < fla.est_step_time
    # Slow host link: the DMA serializes past the saved recompute.
    off, fla = _offload_vs_flash(monkeypatch, dma_bw=3e9)
    assert off.est_dma_time > fla.est_recompute_time
    assert off.est_step_time > fla.est_step_time
    # The iff in one expression: ordering tracks the component trade.
    for bw in (1e12, 64e9, 15e9, 3e9):
        off, fla = _offload_vs_flash(monkeypatch, dma_bw=bw)
        assert (off.est_step_time < fla.est_step_time) == (
            off.est_dma_time < fla.est_recompute_time
        )


def test_search_kernels_enumerates_offload_policy():
    from dlrover_tpu.auto import tune

    cfg = gpt2_config(
        "124m", num_layers=2, d_model=64, num_heads=4, vocab_size=512,
        max_seq_len=512, attention_impl="flash",
    )
    narrow = tune.enumerate_candidates(cfg, 8, seq_len=512)
    assert not any(c.remat == "offload" for c in narrow)
    wide = tune.enumerate_candidates(cfg, 8, search_kernels=True,
                                     seq_len=512)
    assert any(c.remat == "offload" for c in wide)
    # Selective policies are first-class searchable values too.
    sel = tune.enumerate_candidates(
        cfg, 8, remat_policies=("full", "offload:attn_out,mlp_wo"),
        seq_len=512,
    )
    assert any(c.remat == "offload:attn_out,mlp_wo" for c in sel)
    with pytest.raises(ValueError, match="no broadcast encoding"):
        tune.enumerate_candidates(cfg, 8, remat_policies=("offlaod",))


def test_remat_broadcast_codes_roundtrip():
    """Multihost agreement broadcasts the remat choice as an int — every
    enumerable policy (selective offload sets included) must roundtrip."""
    from dlrover_tpu.auto import tune

    names = list(tune._REMAT_CODES) + [
        "offload:qkv_proj", "offload:attn_out,mlp_wo",
        "offload:qkv_proj,flash_out",
    ]
    for name in names:
        assert tune._decode_remat(tune._encode_remat(name)) == name
    # The default offload set folds back to the canonical alias...
    code = tune._encode_remat("offload:qkv_proj,attn_out,mlp_wo")
    assert tune._decode_remat(code) == "offload"
    # ...and order never matters.
    assert tune._encode_remat("offload:mlp_wo,attn_out") == \
        tune._encode_remat("offload:attn_out,mlp_wo")
    with pytest.raises(ValueError):
        tune._encode_remat("no_such_policy")


def test_pick_grad_accum_prefers_smallest_fitting():
    """The tuner picks the smallest feasible N that fits HBM: plentiful
    memory -> N=1; shrinking budgets force more microbatches; a bf16
    accumulator never needs MORE microbatches than fp32 at equal HBM."""
    from dlrover_tpu.auto import pick_grad_accum
    from dlrover_tpu.runtime.mesh import ParallelConfig

    cfg = gpt2_config("1.5b", max_seq_len=2048)
    par = ParallelConfig(data=8)
    roomy = pick_grad_accum(
        cfg, par, 64, 2048, remat="full", hbm_bytes=10_000e9
    )
    assert roomy == 1
    tight = pick_grad_accum(
        cfg, par, 64, 2048, remat="full", hbm_bytes=16e9
    )
    assert tight > 1
    assert 64 % (8 * tight) == 0  # feasible: microbatch divides dp
    bf16 = pick_grad_accum(
        cfg, par, 64, 2048, remat="full", hbm_bytes=16e9,
        accum_dtype="bf16",
    )
    assert bf16 <= tight


def test_est_comm_time_prices_int8_cheaper():
    """est_comm_time: zero without a data axis; int8 beats fp32 on the
    wire for a wire-bound reduce."""
    from dlrover_tpu.auto import est_comm_time
    from dlrover_tpu.runtime.mesh import ParallelConfig

    cfg = gpt2_config("1.5b", max_seq_len=2048)
    assert est_comm_time(cfg, ParallelConfig(data=1, fsdp=8)) == 0.0
    full = est_comm_time(cfg, ParallelConfig(data=8), "none")
    int8 = est_comm_time(cfg, ParallelConfig(data=8), "int8")
    assert full > 0 and int8 > 0
    assert int8 < full
