"""Hybrid mem/disk embedding tier: spill, fault-back, checkpoint, compact."""

import numpy as np
import pytest

from dlrover_tpu.embedding.spill import HybridKVStore, SpillFile
from dlrover_tpu.embedding.table import EmbeddingTable


def test_spill_and_fault_back_preserves_training_state(tmp_path):
    store = HybridKVStore(8, str(tmp_path / "spill.log"), native=False)
    keys = np.array([1, 2, 3], np.int64)
    store.lookup(keys, init_scale=0.1, seed=0, step=1)
    grads = np.random.default_rng(0).normal(size=(3, 8)).astype(np.float32)
    store.apply_group_adam(keys, grads, lr=0.1, t=1)
    rows_before = store.peek(keys).copy()

    # Touch key 3 later so it stays hot; spill the rest.
    store.lookup(np.array([3], np.int64), 0.1, 0, step=10)
    spilled = store.spill(min_step=5, min_count=10)
    assert spilled == 2
    assert store.ram_rows == 1 and store.disk_rows == 2
    assert len(store) == 3

    # peek serves disk rows without promoting them.
    np.testing.assert_allclose(store.peek(keys), rows_before, atol=1e-6)
    assert store.disk_rows == 2

    # lookup faults them back WITH moments: a further identical Adam step
    # on a pure-RAM twin must match exactly.
    twin = HybridKVStore(8, str(tmp_path / "twin.log"), native=False)
    twin.lookup(keys, init_scale=0.1, seed=0, step=1)
    twin.apply_group_adam(keys, grads, lr=0.1, t=1)

    store.lookup(keys, 0.1, 0, step=11)
    assert store.disk_rows == 0 and store.ram_rows == 3
    grads2 = np.ones((3, 8), np.float32)
    store.apply_group_adam(keys, grads2, lr=0.1, t=2)
    twin.apply_group_adam(keys, grads2, lr=0.1, t=2)
    np.testing.assert_allclose(
        store.peek(keys), twin.peek(keys), rtol=1e-6, atol=1e-7
    )
    store.close()
    twin.close()


def test_full_export_spans_both_tiers(tmp_path):
    store = HybridKVStore(4, str(tmp_path / "s.log"), native=False)
    store.lookup(np.arange(6, dtype=np.int64), 0.1, 0, step=1)
    store.lookup(np.array([5], np.int64), 0.1, 0, step=9)
    assert store.spill(min_step=5, min_count=10) == 5
    keys, rows, m, v, counts, steps = store.export()
    assert sorted(keys.tolist()) == [0, 1, 2, 3, 4, 5]
    assert rows.shape == (6, 4)
    # Delta export filters both tiers by recency (spilled rows here are
    # older than the window).
    dkeys, *_ = store.export(min_step=9)
    assert dkeys.tolist() == [5]
    store.close()


def test_spill_log_survives_reopen_and_compacts(tmp_path):
    path = str(tmp_path / "s.log")
    store = HybridKVStore(4, path, native=False)
    store.lookup(np.array([7, 8], np.int64), 0.1, 3, step=1)
    baseline = store.peek(np.array([7, 8], np.int64)).copy()
    store.spill(min_step=2, min_count=10)
    store.close()

    # Fresh process: the index rebuilds from the log.
    reopened = SpillFile(path, 4)
    assert len(reopened) == 2
    row7 = reopened.read(7)[0]
    np.testing.assert_allclose(row7, baseline[0], atol=1e-6)

    # Re-spill a newer generation of key 7, then compact drops the old one.
    reopened.append(7, np.ones(4), np.zeros(4), np.zeros(4), 5, 9)
    reopened.flush()
    size_before = (tmp_path / "s.log").stat().st_size
    reopened.compact()
    assert (tmp_path / "s.log").stat().st_size < size_before
    np.testing.assert_allclose(reopened.read(7)[0], np.ones(4))
    assert reopened.read(7)[3] == 5  # count survived
    reopened.close()


def test_table_level_spill_api(tmp_path):
    table = EmbeddingTable(
        "hybrid", dim=8, learning_rate=0.1,
        spill_path=str(tmp_path / "hybrid.log"),
    )
    table.lookup(np.arange(10, dtype=np.int64))
    for _ in range(20):
        table.lookup(np.array([0, 1], np.int64))  # keep two keys hot
    spilled = table.spill(max_age_steps=5, min_count=3)
    assert spilled == 8
    assert len(table) == 10  # logical size spans both tiers
    # Checkpoint roundtrip includes the spilled tier.
    table.save(str(tmp_path / "ckpt"), step=21)
    fresh = EmbeddingTable("hybrid", dim=8, learning_rate=0.1)
    fresh.restore(str(tmp_path / "ckpt"))
    assert len(fresh) == 10
    table.store.close()


def test_plain_table_rejects_spill():
    table = EmbeddingTable("plain", dim=4)
    with pytest.raises(ValueError, match="hybrid"):
        table.spill(max_age_steps=1)


def test_fault_back_deletion_survives_restart(tmp_path):
    """A faulted-back key's disk record must stay dead across an index
    rebuild — a resurrected stale record would clobber newer training."""
    path = str(tmp_path / "s.log")
    store = HybridKVStore(4, path, native=False)
    keys = np.array([9], np.int64)
    store.lookup(keys, 0.1, 0, step=1)
    store.spill(min_step=2, min_count=10)
    assert store.disk_rows == 1
    store.lookup(keys, 0.1, 0, step=5)           # fault back
    store.apply_group_adam(keys, np.ones((1, 4), np.float32), lr=0.5, t=1)
    trained = store.peek(keys).copy()
    store.disk.flush()
    store.close()

    reopened = SpillFile(path, 4)
    assert 9 not in reopened                     # tombstone honored
    reopened.close()
    # Fresh hybrid store + checkpoint-restore-style insert of the trained
    # row: a later lookup must NOT overwrite it with stale disk state.
    fresh = HybridKVStore(4, path, native=False)
    fresh.insert(keys, trained)
    out = fresh.lookup(keys, 0.1, 0, step=6)
    np.testing.assert_allclose(out, trained, atol=1e-6)
    fresh.close()


def test_insert_tombstones_existing_disk_copy(tmp_path):
    store = HybridKVStore(4, str(tmp_path / "s.log"), native=False)
    keys = np.array([3], np.int64)
    store.lookup(keys, 0.1, 0, step=1)
    store.spill(min_step=2, min_count=10)
    newer = np.full((1, 4), 7.0, np.float32)
    store.insert(keys, newer)
    assert store.disk_rows == 0 and len(store) == 1
    out = store.lookup(keys, 0.1, 0, step=3)     # no stale fault-in
    np.testing.assert_allclose(out, newer)
    store.close()


def test_delta_export_includes_recently_trained_spilled_rows(tmp_path):
    """A row trained inside the delta window then spilled must appear in
    the delta export (restores without the spill file would lose it)."""
    store = HybridKVStore(4, str(tmp_path / "s.log"), native=False)
    store.lookup(np.array([1], np.int64), 0.1, 0, step=100)
    store.lookup(np.array([2], np.int64), 0.1, 0, step=200)
    store.spill(min_step=150, min_count=10)      # spills key 1 (step 100)
    dkeys, *_ = store.export(min_step=91)        # delta window from 91
    assert sorted(dkeys.tolist()) == [1, 2]
    store.close()


def test_truncated_tail_record_is_dropped(tmp_path):
    path = str(tmp_path / "s.log")
    store = HybridKVStore(4, path, native=False)
    store.lookup(np.array([1, 2], np.int64), 0.1, 0, step=1)
    store.spill(min_step=2, min_count=10)
    store.close()
    with open(path, "ab") as f:                  # crash mid-append
        f.write(b"\x07\x00\x00\x00")
    reopened = SpillFile(path, 4)
    assert len(reopened) == 2                    # intact records survive
    assert reopened.read(1) is not None
    reopened.close()
