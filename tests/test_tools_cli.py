"""CI smoke for the tools/ scripts + the timeline dump path end-to-end.

Every ``tools/*.py`` must stay importable (their ``__main__`` guards keep
import side-effect-free), ``tools/job_timeline.py`` must answer ``--help``
as a subprocess, and ``examples/train_lm.py --timeline`` must write a
loadable Chrome trace from a real (tiny, CPU) training run.
"""

import glob
import importlib.util
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_module(path, name):
    spec = importlib.util.spec_from_file_location(name, path)
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    return module


@pytest.mark.parametrize(
    "path",
    sorted(glob.glob(os.path.join(REPO, "tools", "*.py"))),
    ids=lambda p: os.path.basename(p),
)
def test_tools_smoke_import(path):
    """Importing a tool must execute no work (main() is guarded)."""
    _load_module(path, f"_tool_{os.path.basename(path)[:-3]}")


def test_job_timeline_help(cpu_child_env):
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "job_timeline.py"),
         "--help"],
        capture_output=True, text=True, timeout=120, env=cpu_child_env,
    )
    assert out.returncode == 0, out.stderr
    assert "--master" in out.stdout and "--out" in out.stdout


def test_goodput_bench_help(cpu_child_env):
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "goodput_bench.py"),
         "--help"],
        capture_output=True, text=True, timeout=120, env=cpu_child_env,
    )
    assert out.returncode == 0, out.stderr
    assert "--fault-plan" in out.stdout and "--fault-seed" in out.stdout
    assert "--resize-drill" in out.stdout
    assert "--live-relayout" in out.stdout
    # The parity child is an internal spawn target, not operator surface.
    assert "--live-parity-child" not in out.stdout
    assert "--drill-preempt-hit" in out.stdout
    assert "--sdc-drill" in out.stdout
    assert "--sdc-check-every" in out.stdout
    assert "--sdc-flip-hit" in out.stdout


def test_serve_bench_help(cpu_child_env):
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "serve_bench.py"),
         "--help"],
        capture_output=True, text=True, timeout=120, env=cpu_child_env,
    )
    assert out.returncode == 0, out.stderr
    assert "--slots" in out.stdout and "--out" in out.stdout
    assert "--buckets" in out.stdout and "--requests" in out.stdout
    # The serving front-door drill rides the same tool.
    assert "--fleet-drill" in out.stdout
    assert "--replicas" in out.stdout and "--max-pending" in out.stdout
    assert "--deadline-s" in out.stdout and "--slo-p95-s" in out.stdout
    assert "--kill-tick" in out.stdout and "--shed-budget-s" in out.stdout
    # The tensor-parallel serving drill rides the same tool.
    assert "--tp-drill" in out.stdout and "--tp-widths" in out.stdout
    assert "--spec-tokens" in out.stdout and "--draft-layers" in out.stdout
    assert "--draft-damp" in out.stdout and "--accept-floor" in out.stdout


def test_tracelint_json_smoke(tmp_path, cpu_child_env):
    """``tracelint --json`` over a trivially clean dir: exit 0 and a
    well-formed report payload."""
    (tmp_path / "ok.py").write_text("x = 1\n")
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "tracelint.py"),
         str(tmp_path), "--root", str(tmp_path), "--no-baseline",
         "--json"],
        capture_output=True, text=True, timeout=120, env=cpu_child_env,
    )
    assert out.returncode == 0, out.stderr
    payload = json.loads(out.stdout)
    assert payload["findings"] == []
    assert payload["files_checked"] == 1
    assert payload["exit_code"] == 0


def test_tracelint_sarif_smoke(tmp_path, cpu_child_env):
    """``tracelint --format sarif`` over a dirty fixture: exit 1 and a
    valid SARIF 2.1.0 document whose ruleIndex entries agree with the
    advertised driver rules."""
    (tmp_path / "bad.py").write_text(
        "from jax.sharding import PartitionSpec as P\n"
        'SPEC = P("dp", "tesnor")\n'
    )
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "tracelint.py"),
         str(tmp_path), "--root", str(tmp_path), "--no-baseline",
         "--format", "sarif"],
        capture_output=True, text=True, timeout=120, env=cpu_child_env,
    )
    assert out.returncode == 1, out.stdout + out.stderr
    doc = json.loads(out.stdout)
    assert doc["version"] == "2.1.0"
    assert "sarif-schema-2.1.0" in doc["$schema"]
    (run,) = doc["runs"]
    driver = run["tool"]["driver"]
    assert driver["name"] == "tracelint"
    rule_ids = [r["id"] for r in driver["rules"]]
    assert len(rule_ids) >= 12
    assert all(r["shortDescription"]["text"] for r in driver["rules"])
    assert run["results"], "dirty fixture must produce results"
    for result in run["results"]:
        assert rule_ids[result["ruleIndex"]] == result["ruleId"]
        loc = result["locations"][0]["physicalLocation"]
        assert loc["artifactLocation"]["uri"] == "bad.py"
        assert loc["region"]["startLine"] >= 1
    assert any(r["ruleId"] == "SHD001" for r in run["results"])


def test_tracelint_help_smoke(cpu_child_env):
    """``tracelint --help`` exits 0 and advertises the incremental mode."""
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "tracelint.py"),
         "--help"],
        capture_output=True, text=True, timeout=60, env=cpu_child_env,
    )
    assert out.returncode == 0, out.stderr
    assert "--changed" in out.stdout
    assert "--write-baseline" in out.stdout


def test_tracelint_changed_mode(tmp_path, cpu_child_env):
    """``--changed`` lints only the git-diffed files plus their
    reverse-import closure: an edit to a leaf module re-lints its
    importers, while unrelated dirty files stay untouched."""
    repo = tmp_path / "proj"
    pkg = repo / "pkg"
    pkg.mkdir(parents=True)
    bad_spec = (
        "from jax.sharding import PartitionSpec as P\n"
        'SPEC = P("dp", "tesnor")\n'
    )
    (pkg / "base.py").write_text("def f():\n    return 1\n")
    (pkg / "mid.py").write_text(
        "from pkg.base import f\n" + bad_spec +
        "\ndef g():\n    return f()\n"
    )
    (pkg / "loner.py").write_text(bad_spec)
    git = ["git", "-C", str(repo)]
    env = dict(cpu_child_env)
    env.update({
        "GIT_AUTHOR_NAME": "t", "GIT_AUTHOR_EMAIL": "t@t",
        "GIT_COMMITTER_NAME": "t", "GIT_COMMITTER_EMAIL": "t@t",
    })
    for cmd in (["init", "-q"], ["add", "-A"],
                ["commit", "-q", "-m", "seed"]):
        proc = subprocess.run(
            git + cmd, capture_output=True, text=True, timeout=60,
            env=env,
        )
        assert proc.returncode == 0, proc.stderr
    # Dirty the leaf only; mid.py (imports it) must ride the closure,
    # loner.py must not.
    (pkg / "base.py").write_text("def f():\n    return 2\n")

    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "tracelint.py"),
         str(pkg), "--root", str(repo), "--no-baseline", "--changed",
         "--select", "SHD001", "--json"],
        capture_output=True, text=True, timeout=120, env=env,
    )
    payload = json.loads(out.stdout)
    flagged = {f["path"] for f in payload["findings"]}
    assert "pkg/mid.py" in flagged, out.stdout + out.stderr
    assert "pkg/loner.py" not in flagged

    # A clean tree short-circuits: nothing changed, nothing linted.
    subprocess.run(git + ["add", "-A"], capture_output=True, timeout=60,
                   env=env)
    subprocess.run(git + ["commit", "-q", "-m", "fix"],
                   capture_output=True, timeout=60, env=env)
    out2 = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "tracelint.py"),
         str(pkg), "--root", str(repo), "--no-baseline", "--changed"],
        capture_output=True, text=True, timeout=120, env=env,
    )
    assert out2.returncode == 0, out2.stdout + out2.stderr
    assert "nothing to lint" in out2.stdout


def test_serve_bench_gate_predicate():
    """The serve_bench ok gate is a pure predicate: rc 1 exactly when a
    check fails, and the failed check is named."""
    tool = _load_module(
        os.path.join(REPO, "tools", "serve_bench.py"), "_serve_bench"
    )
    continuous = {
        "requests": 8, "tokens": 100, "tokens_per_s": 50.0,
        "p95_s": 0.5, "aot_s": 1.2,
    }
    static = {
        "requests": 8, "tokens": 100, "tokens_per_s": 30.0,
        "p95_s": 0.9, "aot_s": 0.0,
    }
    ledger = {"cached_compiles": 1}
    ok, failed = tool.evaluate_gate(continuous, static, 8, ledger)
    assert ok and failed == []

    slow = dict(continuous, tokens_per_s=10.0)
    ok, failed = tool.evaluate_gate(slow, static, 8, ledger)
    assert not ok and failed == ["throughput_wins"]

    cold = dict(static, aot_s=2.0)
    ok, failed = tool.evaluate_gate(continuous, cold, 8, ledger)
    assert not ok and "warm_start_free" in failed

    short = dict(static, requests=7, tokens=90)
    ok, failed = tool.evaluate_gate(continuous, short, 8, ledger)
    assert not ok
    assert "static_completed" in failed and "token_parity" in failed


def test_serve_fleet_gate_predicate():
    """The --fleet-drill ok gate is a pure predicate over the drill dict:
    every survivability invariant is a named check."""
    tool = _load_module(
        os.path.join(REPO, "tools", "serve_bench.py"), "_serve_bench"
    )
    drill = {
        "submitted": 24, "accepted": 24, "deaths": 1, "resubmitted": 12,
        "lost": 0, "recovered": True, "post_death_completions": 20,
        "p95_post_death_s": 0.4, "slo_p95_s": 1.0,
        "shed": {
            "rejected": True, "reject_s": 0.001, "budget_s": 0.1,
            "cancelled": True, "drained": True,
        },
        "swap": {
            "ok": True, "version": 1, "retraces": 0, "no_drain": True,
        },
        "swap_corrupt": {
            "ok": False, "rolled_back": True, "version": 1,
            "served_after": True,
        },
    }
    ok, failed = tool.evaluate_fleet_gate(drill)
    assert ok and failed == []

    lossy = dict(drill, lost=2)
    ok, failed = tool.evaluate_fleet_gate(lossy)
    assert not ok and failed == ["zero_lost"]

    slow_shed = dict(drill, shed=dict(drill["shed"], reject_s=0.5))
    ok, failed = tool.evaluate_fleet_gate(slow_shed)
    assert not ok and failed == ["shed_fast"]

    retraced = dict(drill, swap=dict(drill["swap"], retraces=3))
    ok, failed = tool.evaluate_fleet_gate(retraced)
    assert not ok and failed == ["swap_zero_retrace"]

    no_rollback = dict(
        drill, swap_corrupt=dict(drill["swap_corrupt"], rolled_back=False)
    )
    ok, failed = tool.evaluate_fleet_gate(no_rollback)
    assert not ok and failed == ["rollback_on_corruption"]

    breached = dict(drill, p95_post_death_s=2.0)
    ok, failed = tool.evaluate_fleet_gate(breached)
    assert not ok and failed == ["p95_recovered_under_slo"]


def _tp_drill_fixture():
    def leg(tp, kv, flops, dbound):
        return {
            "tp": tp, "completed": True, "greedy_parity": True,
            "kv_device_bytes": kv, "device_flops_per_step": flops,
            "device_bound_tokens_per_s": dbound, "steady_retraces": 0,
        }

    return {
        "tp_legs": [
            leg(1, 32776, 228309.0, 1200.0),
            leg(2, 16392, 121313.0, 2258.0),
            leg(4, 8200, 67815.0, 4040.0),
        ],
        "disagg": {
            "requests": 24, "completed": True, "lost": 0,
            "pages_streamed": 24, "decode_step_p95_s": 0.004,
            "colocated_decode_step_p95_s": 0.009,
        },
        "spec": {
            "accept_rate": 0.82, "accept_floor": 0.6,
            "tokens_per_s": 900.0, "plain_tokens_per_s": 600.0,
            "greedy_parity": True,
        },
        "resize": {"completed": True, "warm_fold_retraces": 0},
    }


def test_serve_tp_gate_predicate():
    """The --tp-drill ok gate is a pure predicate over the drill dict:
    each TP/disagg/spec/resize invariant fails as its own named check."""
    tool = _load_module(
        os.path.join(REPO, "tools", "serve_bench.py"), "_serve_bench"
    )
    drill = _tp_drill_fixture()
    ok, failed = tool.evaluate_tp_gate(drill)
    assert ok and failed == []

    divergent = _tp_drill_fixture()
    divergent["tp_legs"][2]["greedy_parity"] = False
    ok, failed = tool.evaluate_tp_gate(divergent)
    assert not ok and failed == ["tp_greedy_parity"]

    unsharded = _tp_drill_fixture()
    unsharded["tp_legs"][2]["kv_device_bytes"] = 32776
    ok, failed = tool.evaluate_tp_gate(unsharded)
    assert not ok
    assert "tp_device_scaling_monotonic" in failed
    assert "tp_kv_bytes_near_ideal" in failed

    retraced = _tp_drill_fixture()
    retraced["tp_legs"][1]["steady_retraces"] = 3
    ok, failed = tool.evaluate_tp_gate(retraced)
    assert not ok and failed == ["tp_zero_steady_retrace"]

    lossy = _tp_drill_fixture()
    lossy["disagg"]["lost"] = 1
    ok, failed = tool.evaluate_tp_gate(lossy)
    assert not ok and failed == ["disagg_zero_lost"]

    unstreamed = _tp_drill_fixture()
    unstreamed["disagg"]["pages_streamed"] = 0
    ok, failed = tool.evaluate_tp_gate(unstreamed)
    assert not ok and failed == ["disagg_pages_streamed"]

    bubbled = _tp_drill_fixture()
    bubbled["disagg"]["decode_step_p95_s"] = 0.02
    ok, failed = tool.evaluate_tp_gate(bubbled)
    assert not ok and failed == ["disagg_decode_p95_wins"]

    rejected = _tp_drill_fixture()
    rejected["spec"]["accept_rate"] = 0.3
    ok, failed = tool.evaluate_tp_gate(rejected)
    assert not ok and failed == ["spec_acceptance_floor"]

    slower = _tp_drill_fixture()
    slower["spec"]["tokens_per_s"] = 500.0
    ok, failed = tool.evaluate_tp_gate(slower)
    assert not ok and failed == ["spec_throughput_wins"]

    drifted = _tp_drill_fixture()
    drifted["spec"]["greedy_parity"] = False
    ok, failed = tool.evaluate_tp_gate(drifted)
    assert not ok and failed == ["spec_greedy_parity"]

    refolded = _tp_drill_fixture()
    refolded["resize"]["warm_fold_retraces"] = 2
    ok, failed = tool.evaluate_tp_gate(refolded)
    assert not ok and failed == ["resize_zero_retrace"]


def test_serve_tp_json_artifact_certified():
    """The committed SERVE_TP.json must be a real certified run: gate
    re-evaluates to ok on the booked numbers, the per-device decode cost
    shrinks with tp, and the greedy streams match across widths."""
    path = os.path.join(REPO, "SERVE_TP.json")
    with open(path) as f:
        result = json.load(f)
    tool = _load_module(
        os.path.join(REPO, "tools", "serve_bench.py"), "_serve_bench2"
    )
    detail = result["detail"]
    ok, failed = tool.evaluate_tp_gate(detail)
    assert ok, f"SERVE_TP.json fails its own gate: {failed}"
    assert detail["ok"] is True
    legs = detail["tp_legs"]
    assert len(legs) >= 2 and legs[0]["tp"] == 1
    assert all(leg["greedy_parity"] for leg in legs)
    assert (
        legs[-1]["device_flops_per_step"]
        < legs[0]["device_flops_per_step"]
    )
    assert detail["spec"]["accept_rate"] >= detail["spec"]["accept_floor"]


def test_embed_bench_help(cpu_child_env):
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "embed_bench.py"),
         "--help"],
        capture_output=True, text=True, timeout=120, env=cpu_child_env,
    )
    assert out.returncode == 0, out.stderr
    assert "--out" in out.stdout and "--num-buckets" in out.stdout
    assert "--bench-steps" in out.stdout and "--world" in out.stdout
    assert "--cache-rows" in out.stdout and "--max-unique" in out.stdout


def test_embed_bench_gate_predicate():
    """The EMBED.json ok gate is a pure predicate: every embedding-plane
    invariant is a named check that fails individually."""
    tool = _load_module(
        os.path.join(REPO, "tools", "embed_bench.py"), "_embed_bench"
    )

    def leg(src, dst):
        return {
            "src": src, "dst": dst, "rows": 100, "moved_rows": 40,
            "reshard_s": 0.01, "row_exact": True, "moments_equal": True,
            "ownership_ok": True,
        }

    result = {
        "parity": {"bitwise_equal": True, "rows_checked": 2848},
        "reshard": {"matrix": [
            leg(s, d) for s in (1, 2, 3, 4) for d in (1, 2, 3, 4)
            if s != d
        ]},
        "hot_path": {"gather_retraces": 0, "scatter_retraces": 0},
        "throughput": {"hit_rate": 0.5, "rows_per_s": 60_000.0},
    }
    ok, failed = tool.evaluate_embed_gate(result)
    assert ok and failed == []

    drifted = dict(result, parity={"bitwise_equal": False,
                                   "rows_checked": 2848})
    ok, failed = tool.evaluate_embed_gate(drifted)
    assert not ok and failed == ["sharded_parity_bitwise"]

    lossy_leg = dict(leg(2, 4), row_exact=False, moments_equal=False)
    lossy = dict(result, reshard={"matrix": (
        result["reshard"]["matrix"][:11] + [lossy_leg]
    )})
    ok, failed = tool.evaluate_embed_gate(lossy)
    assert not ok
    assert "reshard_all_row_exact" in failed
    assert "reshard_moments_intact" in failed

    partial_matrix = dict(
        result, reshard={"matrix": result["reshard"]["matrix"][:11]}
    )
    ok, failed = tool.evaluate_embed_gate(partial_matrix)
    assert not ok and failed == ["reshard_matrix_covered"]

    retraced = dict(result, hot_path={"gather_retraces": 2,
                                      "scatter_retraces": 0})
    ok, failed = tool.evaluate_embed_gate(retraced)
    assert not ok and failed == ["steady_state_no_retrace"]

    cold = dict(result, throughput={"hit_rate": 0.0, "rows_per_s": 0.0})
    ok, failed = tool.evaluate_embed_gate(cold)
    assert not ok
    assert failed == ["cache_hits_happen", "rows_served"]


def test_overlap_bench_help(cpu_child_env):
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "overlap_bench.py"),
         "--help"],
        capture_output=True, text=True, timeout=120, env=cpu_child_env,
    )
    assert out.returncode == 0, out.stderr
    assert "--out" in out.stdout and "--bucket-mb" in out.stdout
    assert "--grad-accum" in out.stdout and "--windows" in out.stdout
    assert "--reduce-quant" in out.stdout
    assert "--allgather-quant" in out.stdout


def test_overlap_bench_gate_predicate():
    """The OVERLAP.json ok gate is a pure predicate; each certification
    leg (measured windows, strictly-higher hidden fraction, tokens/s no
    worse, parity, no retraces) fails as its own named check."""
    tool = _load_module(
        os.path.join(REPO, "tools", "overlap_bench.py"), "_overlap_bench"
    )

    def build(hidden, tokens, retraces=0, windows=3):
        return {
            "windows": windows, "hidden_fraction": hidden,
            "tokens_per_s": tokens, "retraces": retraces,
        }

    result = {
        "serialized": build(0.15, 1000.0),
        "overlapped": build(0.66, 3300.0),
        "parity": {"max_score": 0.8},
    }
    ok, failed = tool.evaluate_overlap_gate(result)
    assert ok and failed == []

    unmeasured = dict(result, overlapped=build(0.66, 3300.0, windows=0))
    ok, failed = tool.evaluate_overlap_gate(unmeasured)
    assert not ok and failed == ["windows_measured"]

    not_higher = dict(result, overlapped=build(0.15, 3300.0))
    ok, failed = tool.evaluate_overlap_gate(not_higher)
    assert not ok and failed == ["overlap_fraction_higher"]

    slower = dict(result, overlapped=build(0.66, 900.0))
    ok, failed = tool.evaluate_overlap_gate(slower)
    assert not ok and failed == ["tokens_per_s_no_worse"]

    drifted = dict(result, parity={"max_score": 1.7})
    ok, failed = tool.evaluate_overlap_gate(drifted)
    assert not ok and failed == ["grad_parity"]

    retraced = dict(result, overlapped=build(0.66, 3300.0, retraces=2))
    ok, failed = tool.evaluate_overlap_gate(retraced)
    assert not ok and failed == ["steady_state_no_retrace"]


def test_overlap_json_artifact_certified():
    """The committed OVERLAP.json must be a real certified run: gate
    re-evaluates to ok on the booked numbers, the overlap is measured
    (capture windows parsed), and the hidden fraction is strictly higher
    for the overlapped build."""
    path = os.path.join(REPO, "OVERLAP.json")
    with open(path) as f:
        result = json.load(f)
    tool = _load_module(
        os.path.join(REPO, "tools", "overlap_bench.py"), "_overlap_bench2"
    )
    ok, failed = tool.evaluate_overlap_gate(result)
    assert ok, f"OVERLAP.json fails its own gate: {failed}"
    assert result["ok"] is True
    assert result["overlapped"]["windows"] >= 1
    assert (
        result["overlapped"]["hidden_fraction"]
        > result["serialized"]["hidden_fraction"]
    )


def test_moe_bench_help(cpu_child_env):
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "moe_bench.py"),
         "--help"],
        capture_output=True, text=True, timeout=120, env=cpu_child_env,
    )
    assert out.returncode == 0, out.stderr
    assert "--out" in out.stdout and "--experts" in out.stdout
    assert "--top-k" in out.stdout and "--capacity-factor" in out.stdout
    assert "--dispatch" in out.stdout and "--resize-steps" in out.stdout


def _moe_result():
    """A MOE.json-shaped dict that passes every gate check — the
    single-mutation matrix below breaks one leg at a time."""
    return {
        "dense": {"tokens_per_s": 2000.0, "retraces": 0},
        "moe": {"tokens_per_s": 7000.0, "retraces": 0},
        "wire": {"payload_elems": 20480, "fp32_bytes": 81920,
                 "int8_bytes": 20800},
        "resize": {"expert_leaves": 3, "bitwise_equal": True},
    }


def test_moe_bench_gate_predicate():
    """The MOE.json ok gate is a pure predicate; each certification leg
    (throughput vs the dense iso-FLOP baseline, int8 wire discount,
    bitwise resize parity, zero retraces) fails as its own named check."""
    import copy

    tool = _load_module(
        os.path.join(REPO, "tools", "moe_bench.py"), "_moe_bench"
    )
    ok, failed = tool.evaluate_moe_gate(_moe_result())
    assert ok and failed == []

    def mutate(fn):
        result = copy.deepcopy(_moe_result())
        fn(result)
        return tool.evaluate_moe_gate(result)

    ok, failed = mutate(lambda r: r["moe"].update(tokens_per_s=1500.0))
    assert not ok and failed == ["moe_tokens_per_s_beats_dense"]

    ok, failed = mutate(lambda r: r["wire"].update(int8_bytes=90000))
    assert not ok and failed == ["int8_dispatch_wire_cheaper"]

    ok, failed = mutate(lambda r: r["resize"].update(bitwise_equal=False))
    assert not ok and failed == ["resize_expert_state_bitwise"]

    # An empty expert-leaf set must fail too: "nothing compared" is not
    # parity.
    ok, failed = mutate(lambda r: r["resize"].update(expert_leaves=0))
    assert not ok and failed == ["resize_expert_state_bitwise"]

    ok, failed = mutate(lambda r: r["moe"].update(retraces=2))
    assert not ok and failed == ["steady_state_no_retrace"]


def test_moe_json_artifact_certified():
    """The committed MOE.json must be a real certified run: the gate
    re-evaluates to ok on the booked numbers, the MoE build beat the
    dense iso-FLOP baseline, and the fold preserved expert state."""
    path = os.path.join(REPO, "MOE.json")
    with open(path) as f:
        result = json.load(f)
    tool = _load_module(
        os.path.join(REPO, "tools", "moe_bench.py"), "_moe_bench2"
    )
    ok, failed = tool.evaluate_moe_gate(result)
    assert ok, f"MOE.json fails its own gate: {failed}"
    assert result["ok"] is True
    assert result["moe"]["tokens_per_s"] > result["dense"]["tokens_per_s"]
    assert result["wire"]["int8_bytes"] < result["wire"]["fp32_bytes"]
    assert result["resize"]["bitwise_equal"] is True
    assert result["resize"]["expert_leaves"] >= 1
    assert result["config"]["d_ff_dense"] == (
        result["config"]["experts"] * result["config"]["d_ff_expert"]
    )


def test_train_lm_moe_flags_help(cpu_child_env):
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples", "train_lm.py"),
         "--help"],
        capture_output=True, text=True, timeout=120, env=cpu_child_env,
    )
    assert out.returncode == 0, out.stderr
    assert "--moe-experts" in out.stdout
    assert "--moe-top-k" in out.stdout
    assert "--moe-capacity-factor" in out.stdout
    assert "--moe-dispatch" in out.stdout
    assert "a2a_int8" in out.stdout


def test_train_rec_help(cpu_child_env):
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples", "train_rec.py"),
         "--help"],
        capture_output=True, text=True, timeout=120, env=cpu_child_env,
    )
    assert out.returncode == 0, out.stderr
    assert "--num-buckets" in out.stdout and "--world" in out.stdout
    assert "--cache-rows" in out.stdout and "--max-unique" in out.stdout
    assert "--prefetch-depth" in out.stdout
    assert "--reshard-at" in out.stdout
    assert "--sparse-optimizer" in out.stdout


def test_train_wide_deep_help(cpu_child_env):
    out = subprocess.run(
        [sys.executable,
         os.path.join(REPO, "examples", "train_wide_deep.py"), "--help"],
        capture_output=True, text=True, timeout=120, env=cpu_child_env,
    )
    assert out.returncode == 0, out.stderr
    assert "--id-space" in out.stdout and "--dim" in out.stdout
    assert "--sparse-optimizer" in out.stdout
    assert "--evict-every" in out.stdout


@pytest.mark.slow
def test_train_rec_short_e2e(tmp_path, monkeypatch, capfd):
    """A tiny real train_rec run: trains, reshards mid-run, checkpoints
    the sharded plane, and exits 0 (standalone mode, CPU)."""
    sys.path.insert(0, os.path.join(REPO, "examples"))
    try:
        import train_rec
    finally:
        sys.path.pop(0)
    ckpt = tmp_path / "rec_ckpt"
    monkeypatch.setattr(sys, "argv", [
        "train_rec.py", "--steps", "6", "--batch-size", "16",
        "--fields", "4", "--id-space", "500", "--dim", "8",
        "--hidden", "16", "--world", "2", "--num-buckets", "8",
        "--cache-rows", "128", "--max-unique", "64",
        "--reshard-at", "3:1", "--checkpoint-dir", str(ckpt),
        "--ckpt-every", "4",
    ])
    assert train_rec.main() == 0
    err = capfd.readouterr().err
    assert "resharded 2 -> 1 owners at step 3" in err
    assert os.listdir(ckpt), "plane checkpoint must land on disk"


def test_metrics_scrape_help(cpu_child_env):
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "metrics_scrape.py"),
         "--help"],
        capture_output=True, text=True, timeout=120, env=cpu_child_env,
    )
    assert out.returncode == 0, out.stderr
    assert "--url" in out.stdout and "--timeline-out" in out.stdout


def test_metrics_scrape_against_live_plane(tmp_path, monkeypatch, capsys):
    """End-to-end: the scrape CLI against a real in-process HTTP plane —
    every endpoint answers and the timeline lands on disk."""
    from dlrover_tpu.master.http_plane import MetricsHTTPServer
    from dlrover_tpu.master.servicer import MasterServicer
    from dlrover_tpu.master.timeline import JobTimeline

    timeline = JobTimeline()
    timeline.record(0, "step", kind="span", duration_s=0.1,
                    attrs={"step": 1})
    plane = MetricsHTTPServer(
        MasterServicer(timeline=timeline), host="127.0.0.1", port=0
    )
    port = plane.start()
    tool = _load_module(
        os.path.join(REPO, "tools", "metrics_scrape.py"), "_metrics_scrape"
    )
    out = tmp_path / "timeline.json"
    monkeypatch.setattr(sys, "argv", [
        "metrics_scrape.py", "--url", f"http://127.0.0.1:{port}",
        "--timeline-out", str(out),
    ])
    try:
        assert tool.main() == 0
    finally:
        plane.stop()
    report = capsys.readouterr().out
    assert "healthz: ok=True" in report
    assert "metrics:" in report and "FAILED" not in report
    trace = json.loads(out.read_text())
    assert any(e.get("name") == "step" for e in trace["traceEvents"])
    # A dead endpoint is a nonzero exit, not a crash.
    monkeypatch.setattr(sys, "argv", [
        "metrics_scrape.py", "--url", f"http://127.0.0.1:{port}",
        "--timeout", "0.5",
    ])
    assert tool.main() == 1


def test_job_timeline_converts_wire_dump(tmp_path, monkeypatch):
    events = {
        "0": [["step", "span", 10.0, 0.2, {"src": "trainer", "step": 1}],
              ["restart", "event", 11.0, 0.0, {"src": "agent"}]],
        "1": [["step", "span", 10.05, 0.21, {"src": "trainer", "step": 1}]],
    }
    src = tmp_path / "events.json"
    src.write_text(json.dumps(events))
    out = tmp_path / "trace.json"
    tool = _load_module(
        os.path.join(REPO, "tools", "job_timeline.py"), "_job_timeline"
    )
    monkeypatch.setattr(sys, "argv", [
        "job_timeline.py", "--input", str(src), "--out", str(out),
    ])
    assert tool.main() == 0
    trace = json.loads(out.read_text())
    slices = [e for e in trace["traceEvents"] if e["ph"] == "X"]
    assert {e["pid"] for e in slices} == {0, 1}
    assert any(e["ph"] == "i" for e in trace["traceEvents"])


@pytest.mark.slow  # subprocess jax import + compile, ~8s on 1 core
def test_trace_steps_microbatch_phases():
    """With the microbatch engine on, trace_steps attaches per-microbatch
    accumulate/reduce/update phase rows that tile the measured step."""
    tool = _load_module(
        os.path.join(REPO, "tools", "trace_steps.py"), "_trace_steps"
    )
    out = tool.run_trace(
        steps=2, metrics_lag=0, prefetch=0, batch=16,
        grad_accum=2, reduce_quant="int8",
    )
    assert out["grad_accum"] == 2
    rows = out["microbatch_phases"]
    assert [r["phase"] for r in rows] == [
        "accumulate", "accumulate", "reduce", "update",
    ]
    assert [r["micro"] for r in rows] == [0, 1, -1, -1]
    assert all(r["dur_s"] > 0 for r in rows)


@pytest.mark.slow  # subprocess jax import + compile, ~4s on 1 core
def test_train_lm_timeline_flag(tmp_path, monkeypatch):
    """The example's ``--timeline`` writes a Chrome trace holding the run's
    step spans (standalone mode: the local ring is the source)."""
    sys.path.insert(0, os.path.join(REPO, "examples"))
    try:
        import train_lm
    finally:
        sys.path.pop(0)
    from dlrover_tpu.common import telemetry

    recorder = telemetry.recorder()
    was_enabled = recorder.enabled
    recorder.configure(enabled=True)
    recorder.drain()
    out = tmp_path / "trace.json"
    monkeypatch.setattr(sys, "argv", [
        "train_lm.py", "--steps", "3", "--layers", "1", "--d-model", "32",
        "--heads", "2", "--vocab", "64", "--seq-len", "16",
        "--batch-size", "8", "--timeline", str(out),
    ])
    try:
        assert train_lm.main() == 0
    finally:
        recorder.configure(enabled=was_enabled)
    trace = json.loads(out.read_text())
    steps = [
        e for e in trace["traceEvents"]
        if e.get("name") == "step" and e["ph"] == "X"
    ]
    assert len(steps) == 3
    assert sorted(e["args"]["step"] for e in steps) == [1, 2, 3]


def test_memory_bench_help(cpu_child_env):
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "memory_bench.py"),
         "--help"],
        capture_output=True, text=True, timeout=120, env=cpu_child_env,
    )
    assert out.returncode == 0, out.stderr
    assert "--out" in out.stdout and "--grad-accum" in out.stdout
    assert "--live-steps" in out.stdout and "--serve-slots" in out.stdout


def _memory_result():
    """A MEMORY.json-shaped dict that passes every gate check — the
    single-mutation matrix below breaks one leg at a time."""
    return {
        "param_opt": {
            "measured_params_b": 482304, "measured_opt_b": 964612,
            "modeled_params_b": 482304, "modeled_opt_b": 964612,
        },
        "zero1": {"legs": [
            {"dp": 1, "measured_opt_b": 964612, "modeled_opt_b": 0},
            {"dp": 2, "measured_opt_b": 482308, "modeled_opt_b": 482308},
            {"dp": 4, "measured_opt_b": 241156, "modeled_opt_b": 241156},
        ]},
        "kv": {"legs": [
            {"tp": 1, "measured_kv_b": 65544},
            {"tp": 2, "measured_kv_b": 32776},
        ]},
        "accum": {"temp_delta_b": 241152, "accum_half_b": 241152},
        "live": {
            "events": 4,
            "ledger": {"bytes_in_use": 5789840.0,
                       "pool_params_b": 482304.0,
                       "pool_opt_state_b": 964612.0},
            "gauges_rendered": True,
            "calibration_memory_ratio": 4.0,
            "retraces": 0,
        },
        "postmortem": {"rows": 8, "top_pool": "params",
                       "pools": ["params", "opt_state", "other"]},
    }


def test_memory_bench_gate_predicate():
    """The MEMORY.json ok gate is a pure predicate; each certification
    leg fails as its own named check."""
    import copy

    tool = _load_module(
        os.path.join(REPO, "tools", "memory_bench.py"), "_memory_bench"
    )
    ok, failed = tool.evaluate_memory_gate(_memory_result())
    assert ok and failed == []

    def mutate(fn):
        result = copy.deepcopy(_memory_result())
        fn(result)
        return tool.evaluate_memory_gate(result)

    ok, failed = mutate(
        lambda r: r["param_opt"].update(measured_params_b=300000))
    assert not ok and failed == ["params_match_shape_model"]

    ok, failed = mutate(
        lambda r: r["param_opt"].update(measured_opt_b=300000))
    assert not ok and failed == ["opt_state_matches_shape_model"]

    # Not falling: dp=4 measures the full replicated bytes (measured and
    # modeled agree, so only the 1/dp law fails).
    ok, failed = mutate(lambda r: r["zero1"]["legs"][2].update(
        measured_opt_b=964612, modeled_opt_b=964612))
    assert not ok and failed == ["zero1_opt_bytes_fall_with_dp"]

    ok, failed = mutate(lambda r: r["zero1"]["legs"][2].update(
        modeled_opt_b=400000))
    assert not ok and failed == ["zero1_measured_matches_model"]

    ok, failed = mutate(lambda r: r["kv"]["legs"][1].update(
        measured_kv_b=60000))
    assert not ok and failed == ["kv_pool_falls_with_tp"]

    ok, failed = mutate(lambda r: r["accum"].update(temp_delta_b=100000))
    assert not ok and failed == ["accum_bf16_halves_accumulator"]

    ok, failed = mutate(lambda r: r["live"].update(events=0))
    assert not ok and failed == ["live_events_flow"]

    ok, failed = mutate(lambda r: r["live"].update(gauges_rendered=False))
    assert not ok and failed == ["live_gauges_render"]

    ok, failed = mutate(
        lambda r: r["live"].update(calibration_memory_ratio=0.0))
    assert not ok and failed == ["calibration_learned_memory_ratio"]

    ok, failed = mutate(lambda r: r["live"].update(retraces=2))
    assert not ok and failed == ["steady_state_no_retrace"]

    ok, failed = mutate(lambda r: r["postmortem"].update(rows=0))
    assert not ok and failed == ["postmortem_classified"]


def test_memory_json_artifact_certified():
    """The committed MEMORY.json must be a real certified run: the gate
    re-evaluates to ok on the booked numbers, ZeRO-1 opt bytes fall with
    dp, and the live leg held zero steady-state retraces."""
    path = os.path.join(REPO, "MEMORY.json")
    with open(path) as f:
        result = json.load(f)
    tool = _load_module(
        os.path.join(REPO, "tools", "memory_bench.py"), "_memory_bench2"
    )
    ok, failed = tool.evaluate_memory_gate(result)
    assert ok, f"MEMORY.json fails its own gate: {failed}"
    assert result["ok"] is True
    opt = [leg["measured_opt_b"] for leg in result["zero1"]["legs"]]
    assert opt[0] > opt[1] > opt[2]
    assert result["live"]["retraces"] == 0
    assert result["accum"]["temp_delta_b"] > 0


def test_metrics_scrape_memory_endpoint(monkeypatch, capsys):
    """The scrape CLI probes /memory against a live plane holding a
    populated MemoryLedger."""
    from dlrover_tpu.master.http_plane import MetricsHTTPServer
    from dlrover_tpu.master.memory_ledger import MemoryLedger
    from dlrover_tpu.master.servicer import MasterServicer
    from dlrover_tpu.master.timeline import JobTimeline

    ledger = MemoryLedger()
    ledger.record(0, bytes_in_use=800.0, peak_bytes=900.0,
                  limit_bytes=1000.0, headroom_frac=0.2,
                  pool_params_b=500.0)
    plane = MetricsHTTPServer(
        MasterServicer(timeline=JobTimeline(), memory_ledger=ledger),
        host="127.0.0.1", port=0,
    )
    port = plane.start()
    tool = _load_module(
        os.path.join(REPO, "tools", "metrics_scrape.py"),
        "_metrics_scrape_mem",
    )
    monkeypatch.setattr(sys, "argv", [
        "metrics_scrape.py", "--url", f"http://127.0.0.1:{port}",
    ])
    try:
        assert tool.main() == 0
    finally:
        plane.stop()
    report = capsys.readouterr().out
    assert "memory: nodes=1 bytes_in_use=800 headroom=0.200" in report
    assert "hbm_headroom=0.2" in report
    assert "FAILED" not in report
