"""Production TpuVmHttpClient against a local fake Cloud TPU API server.

VERDICT r4 missing #2: the reference ships a working cluster client
(``dlrover/python/scheduler/kubernetes.py:1-572``); this drives our HTTP
client — and the full CloudNodeLauncher above it — against an in-process
HTTP server speaking the real ``tpu.googleapis.com`` v2 JSON shapes
(create/get/list/delete, operations, error envelopes, pagination,
metadata-server token minting).
"""

import json
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from dlrover_tpu.master.cloud_launcher import (
    CloudError,
    CloudNodeLauncher,
    TpuVmState,
)
from dlrover_tpu.master.tpu_api import TpuVmHttpClient, map_node_state

PROJECT, ZONE = "test-proj", "us-central2-b"
NODES_PATH = f"/v2/projects/{PROJECT}/locations/{ZONE}/nodes"
TOKEN_PATH = "/computeMetadata/v1/instance/service-accounts/default/token"


class FakeCloud:
    """Server-side state: nodes keyed by short id, injectable failures."""

    def __init__(self):
        self.lock = threading.Lock()
        self.nodes = {}
        self.fail_creates = 0
        self.tokens_minted = 0
        self.page_size = 0  # 0 = no pagination

    def qualified(self, name):
        return f"projects/{PROJECT}/locations/{ZONE}/nodes/{name}"


class Handler(BaseHTTPRequestHandler):
    cloud: FakeCloud = None  # injected per-test

    def log_message(self, *args):
        pass

    def _send(self, code, payload):
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _error(self, code, status, message):
        self._send(code, {
            "error": {"code": code, "status": status, "message": message}
        })

    def _auth_ok(self):
        auth = self.headers.get("Authorization", "")
        if not auth.startswith("Bearer tok-"):
            self._error(401, "UNAUTHENTICATED", "bad token")
            return False
        return True

    def do_GET(self):
        url = urllib.parse.urlparse(self.path)
        if url.path == TOKEN_PATH:
            if self.headers.get("Metadata-Flavor") != "Google":
                self._error(403, "PERMISSION_DENIED", "no flavor header")
                return
            self.cloud.tokens_minted += 1
            self._send(200, {
                "access_token": f"tok-{self.cloud.tokens_minted}",
                "expires_in": 3600, "token_type": "Bearer",
            })
            return
        if not self._auth_ok():
            return
        with self.cloud.lock:
            if url.path == NODES_PATH:  # list
                names = sorted(self.cloud.nodes)
                query = urllib.parse.parse_qs(url.query)
                start = int(query.get("pageToken", ["0"])[0] or 0)
                if self.cloud.page_size:
                    page = names[start:start + self.cloud.page_size]
                    nxt = start + self.cloud.page_size
                    payload = {
                        "nodes": [self.cloud.nodes[n] for n in page]
                    }
                    if nxt < len(names):
                        payload["nextPageToken"] = str(nxt)
                else:
                    payload = {"nodes": [self.cloud.nodes[n] for n in names]}
                self._send(200, payload)
                return
            if url.path.startswith(NODES_PATH + "/"):  # get
                name = url.path.rsplit("/", 1)[-1]
                node = self.cloud.nodes.get(name)
                if node is None:
                    self._error(404, "NOT_FOUND", f"node {name}")
                    return
                self._send(200, node)
                return
        self._error(404, "NOT_FOUND", url.path)

    def do_POST(self):
        url = urllib.parse.urlparse(self.path)
        if not self._auth_ok():
            return
        if url.path != NODES_PATH:
            self._error(404, "NOT_FOUND", url.path)
            return
        name = urllib.parse.parse_qs(url.query).get("nodeId", [""])[0]
        length = int(self.headers.get("Content-Length", 0))
        body = json.loads(self.rfile.read(length))
        with self.cloud.lock:
            if self.cloud.fail_creates > 0:
                self.cloud.fail_creates -= 1
                self._error(
                    429, "RESOURCE_EXHAUSTED",
                    "no capacity for this accelerator type",
                )
                return
            if name in self.cloud.nodes:
                self._error(409, "ALREADY_EXISTS", name)
                return
            self.cloud.nodes[name] = {
                "name": self.cloud.qualified(name),
                "acceleratorType": body["acceleratorType"],
                "runtimeVersion": body["runtimeVersion"],
                "metadata": body.get("metadata", {}),
                "state": "READY",  # instant provisioning in the fake
            }
        self._send(200, {  # long-running operation envelope
            "name": f"projects/{PROJECT}/locations/{ZONE}/operations/op-1",
            "done": False,
        })

    def do_DELETE(self):
        url = urllib.parse.urlparse(self.path)
        if not self._auth_ok():
            return
        name = url.path.rsplit("/", 1)[-1]
        with self.cloud.lock:
            if name not in self.cloud.nodes:
                self._error(404, "NOT_FOUND", name)
                return
            del self.cloud.nodes[name]
        self._send(200, {"name": "operations/op-2", "done": False})


@pytest.fixture()
def fake_cloud():
    cloud = FakeCloud()
    handler = type("H", (Handler,), {"cloud": cloud})
    server = ThreadingHTTPServer(("127.0.0.1", 0), handler)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    cloud.url = f"http://127.0.0.1:{server.server_port}"
    yield cloud
    server.shutdown()
    server.server_close()


def _client(cloud):
    return TpuVmHttpClient(
        project=PROJECT, zone=ZONE,
        base_url=cloud.url + "/v2",
        metadata_host=cloud.url,
    )


def test_crud_roundtrip_with_real_json_shapes(fake_cloud):
    client = _client(fake_cloud)
    client.create_node(
        "job-worker-0", "v5litepod-8", "tpu-ubuntu2204-base",
        {"dlrover-master-addr": "10.0.0.2:50051"},
    )
    node = client.get_node("job-worker-0")
    assert node["state"] == TpuVmState.READY
    assert node["name"] == "job-worker-0"  # unqualified for the launcher
    assert node["metadata"]["dlrover-master-addr"] == "10.0.0.2:50051"
    assert client.get_node("nope") is None
    listed = client.list_nodes()
    assert [n["name"] for n in listed] == ["job-worker-0"]
    client.delete_node("job-worker-0")
    assert client.get_node("job-worker-0") is None
    with pytest.raises(CloudError, match="NOT_FOUND"):
        client.delete_node("job-worker-0")


def test_create_conflict_and_stockout_map_to_cloud_errors(fake_cloud):
    client = _client(fake_cloud)
    client.create_node("n0", "v5litepod-8", "rt", {})
    with pytest.raises(CloudError, match="ALREADY_EXISTS"):
        client.create_node("n0", "v5litepod-8", "rt", {})
    fake_cloud.fail_creates = 1
    with pytest.raises(CloudError, match="RESOURCE_EXHAUSTED"):
        client.create_node("n1", "v5litepod-8", "rt", {})


def test_token_cached_until_expiry(fake_cloud):
    client = _client(fake_cloud)
    client.create_node("n0", "v5litepod-8", "rt", {})
    client.get_node("n0")
    client.list_nodes()
    assert fake_cloud.tokens_minted == 1  # one mint covers all calls
    client._token_expiry = 0.0  # force expiry
    client.get_node("n0")
    assert fake_cloud.tokens_minted == 2


def test_list_pagination(fake_cloud):
    client = _client(fake_cloud)
    for i in range(5):
        client.create_node(f"n{i}", "v5litepod-8", "rt", {})
    fake_cloud.page_size = 2  # forces 3 pages
    assert sorted(n["name"] for n in client.list_nodes()) == [
        f"n{i}" for i in range(5)
    ]


def test_state_mapping_covers_repair_states():
    assert map_node_state("REPAIRING") == TpuVmState.CREATING
    assert map_node_state("RESTARTING") == TpuVmState.CREATING
    assert map_node_state("PREEMPTED") == TpuVmState.PREEMPTED
    assert map_node_state("STOPPED") == TpuVmState.TERMINATED
    assert map_node_state("SOMETHING_NEW") == TpuVmState.CREATING


def test_launcher_drives_http_client_launch_preempt_relaunch(fake_cloud):
    """The full integration the VERDICT asked for: CloudNodeLauncher
    launch -> READY -> preempt -> reconcile maps dead -> relaunch lands a
    fresh VM — all over HTTP against the fake API."""
    client = _client(fake_cloud)
    launcher = CloudNodeLauncher(
        client, job_name="job", master_addr="10.0.0.2:50051",
    )
    launcher.RETRY_BACKOFF_S = 0.05
    try:
        launcher.launch(0)
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            node = client.get_node("job-worker-0")
            if node and node["state"] == TpuVmState.READY:
                break
            time.sleep(0.05)
        assert client.get_node("job-worker-0")["state"] == TpuVmState.READY

        # Preemption seen through reconcile.
        with fake_cloud.lock:
            fake_cloud.nodes["job-worker-0"]["state"] = "PREEMPTED"
        assert launcher.reconcile() == {0: TpuVmState.PREEMPTED}

        # Relaunch: the launcher clears the dead VM and creates anew.
        launcher.launch(0)
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            node = client.get_node("job-worker-0")
            if node and node["state"] == TpuVmState.READY:
                break
            time.sleep(0.05)
        assert client.get_node("job-worker-0")["state"] == TpuVmState.READY
        assert launcher.reconcile() == {0: TpuVmState.READY}
    finally:
        launcher.shutdown()


def test_stockout_retries_then_succeeds_through_launcher(fake_cloud):
    client = _client(fake_cloud)
    launcher = CloudNodeLauncher(client, job_name="job")
    launcher.RETRY_BACKOFF_S = 0.05
    fake_cloud.fail_creates = 2  # transient stockout, 3rd attempt lands
    try:
        launcher.launch(0)
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            node = client.get_node("job-worker-0")
            if node is not None:
                break
            time.sleep(0.05)
        assert client.get_node("job-worker-0")["state"] == TpuVmState.READY
    finally:
        launcher.shutdown()


def test_project_zone_resolution_requires_config(monkeypatch):
    monkeypatch.delenv("GCP_PROJECT", raising=False)
    monkeypatch.delenv("TPU_ZONE", raising=False)
    with pytest.raises(CloudError, match="INVALID_ARGUMENT"):
        TpuVmHttpClient(metadata_host="http://127.0.0.1:1")  # no metadata
