"""Distributed embedding plane: hash-bucketed sharding, bitwise parity
with a single-host reference, elastic n→m resharding with optimizer
moments intact, digest-chained export/restore, and the HBM hot-row cache
(device parity, LRU eviction, writeback, steady-state no-retrace)."""

import os

import numpy as np
import pytest

import jax.numpy as jnp

from dlrover_tpu.common import faults
from dlrover_tpu.embedding import (
    DeviceHotRowCache,
    EmbeddingPrefetcher,
    ShardedEmbeddingTable,
    hash_bucket,
)
from dlrover_tpu.embedding import kernels
from dlrover_tpu.runtime.virtual_mesh import shard_owner
from tests import trace_asserts

DIM = 8


def make_plane(world, **kw):
    kw.setdefault("num_buckets", 16)
    kw.setdefault("learning_rate", 0.05)
    kw.setdefault("seed", 3)
    return ShardedEmbeddingTable("plane", dim=DIM, world=world, **kw)


def drive(plane, steps=4, seed=0, batch=64):
    """Deterministic lookup+gradient stream, replayable on any fold."""
    rng = np.random.default_rng(seed)
    for _ in range(steps):
        keys = rng.integers(0, 500, size=batch).astype(np.int64)
        _, uniq, _ = plane.lookup(keys)
        grads = np.outer(
            (uniq % 13 - 6).astype(np.float32) * 0.02,
            np.ones(DIM, np.float32),
        )
        plane.apply_gradients(uniq, grads)
    return plane


def snapshot(plane):
    """{key: (value, m, v, count)} across every owner host."""
    out = {}
    for store in plane._hosts:
        keys, rows, m, v, counts, _ = store.export()
        for i, key in enumerate(keys.tolist()):
            out[key] = (rows[i].copy(), m[i].copy(), v[i].copy(),
                        int(counts[i]))
    return out


# -- geometry ----------------------------------------------------------------


def test_hash_bucket_is_deterministic_and_spread():
    keys = np.arange(10_000, dtype=np.int64)
    a = hash_bucket(keys, 64)
    b = hash_bucket(keys.copy(), 64)
    np.testing.assert_array_equal(a, b)
    assert a.min() >= 0 and a.max() < 64
    # splitmix64 must actually spread sequential ids (a modulo would not).
    filled = np.bincount(a, minlength=64)
    assert filled.min() > 0 and filled.max() < filled.mean() * 2


def test_bucket_fold_agrees_with_the_virtual_mesh_rule():
    """One ownership rule across the repo: the plane's bucket→owner map
    IS ``shard_owner`` — the virtual mesh's fold."""
    plane = make_plane(world=3)
    keys = np.arange(200, dtype=np.int64)
    buckets = plane.bucket_of(keys)
    owners = plane.owner_of(keys)
    for bucket, owner in zip(buckets.tolist(), owners.tolist()):
        assert owner == shard_owner(bucket, 3)
    for rank in range(3):
        for bucket in plane.owned_buckets(rank):
            assert shard_owner(bucket, 3) == rank
    plane.close()


def test_world_cannot_exceed_bucket_space():
    with pytest.raises(ValueError):
        make_plane(world=32, num_buckets=16)
    plane = make_plane(world=2)
    with pytest.raises(ValueError):
        plane.reshard(17)
    plane.close()


# -- sharded == single host ---------------------------------------------------


@pytest.mark.parametrize("world", [2, 4])
def test_sharded_lookup_and_update_match_single_host_bitwise(world):
    sharded = drive(make_plane(world))
    reference = drive(make_plane(1))
    keys = np.arange(500, dtype=np.int64)
    np.testing.assert_array_equal(sharded.peek(keys), reference.peek(keys))
    assert len(sharded) == len(reference)
    sharded.close()
    reference.close()


def test_lookup_returns_unique_inverse_contract():
    plane = make_plane(2)
    rows, uniq, inverse = plane.lookup(
        np.array([[9, 4], [4, 9]], np.int64)
    )
    assert rows.shape == (2, DIM)
    np.testing.assert_array_equal(uniq, [4, 9])
    np.testing.assert_array_equal(inverse, [1, 0, 0, 1])
    np.testing.assert_array_equal(rows[inverse][0], rows[inverse][3])
    plane.close()


# -- elastic resharding -------------------------------------------------------


@pytest.mark.parametrize("src,dst", [
    (1, 2), (1, 4), (2, 1), (2, 4), (4, 1), (4, 2),
    # Non-divisor folds: a bucket's owner can change to a host that is
    # ALSO a migration source (e.g. 3→2: bucket 3 moves host 0 → host 1
    # while host 1 is still pending).  Selecting movers by old-fold vs
    # new-fold instead of new-owner vs current-host loses those rows.
    (3, 2), (2, 3), (4, 3), (3, 4), (4, 6), (6, 4),
])
def test_reshard_matrix_rows_and_moments_exact(src, dst):
    plane = drive(make_plane(src))
    before = snapshot(plane)
    summary = plane.reshard(dst)
    after = snapshot(plane)
    assert plane.world == dst
    assert set(before) == set(after)
    for key in before:
        for leg in range(3):  # value, m, v bitwise
            np.testing.assert_array_equal(before[key][leg], after[key][leg])
        assert before[key][3] == after[key][3]
    # Every surviving row obeys the new fold; retired hosts are gone.
    for rank in range(dst):
        keys = plane._hosts[rank].export()[0]
        np.testing.assert_array_equal(
            plane.owner_of(keys), np.full(keys.shape, rank)
        )
    assert summary["src"] == src and summary["dst"] == dst
    if src != dst:
        assert summary["moved_rows"] > 0
    plane.close()


def test_reshard_non_divisor_chain_is_lossless():
    """3→2→3 round trip over a dense population: every row and moment
    survives both non-divisor folds bitwise (regression for the
    migrated-row-re-selected-at-a-later-source row-loss bug)."""
    plane = drive(make_plane(3), steps=8, batch=256)
    before = snapshot(plane)
    assert len(before) > 400  # dense enough to populate every bucket pair
    plane.reshard(2)
    assert len(plane) == len(before)
    plane.reshard(3)
    after = snapshot(plane)
    assert set(before) == set(after)
    for key in before:
        for leg in range(3):
            np.testing.assert_array_equal(before[key][leg], after[key][leg])
    plane.close()


def test_reshard_then_training_still_matches_reference():
    """The acceptance loop: train → re-fold → keep training must equal a
    never-resharded single-host run bit for bit (plane-global clock)."""
    elastic = drive(make_plane(4), steps=3, seed=1)
    elastic.reshard(2)
    drive(elastic, steps=3, seed=2)
    reference = drive(make_plane(1), steps=3, seed=1)
    drive(reference, steps=3, seed=2)
    keys = np.arange(500, dtype=np.int64)
    np.testing.assert_array_equal(
        elastic.peek(keys), reference.peek(keys)
    )
    elastic.close()
    reference.close()


def test_reshard_with_spill_tier_moves_cold_rows(tmp_path):
    plane = ShardedEmbeddingTable(
        "spilled", dim=DIM, num_buckets=16, world=2, learning_rate=0.05,
        seed=3, spill_dir=str(tmp_path),
    )
    drive(plane)
    # Push everything cold so the move has to read through the disk tier.
    for host in plane._hosts:
        host.spill(min_step=plane.step + 1, min_count=10**6)
    before = snapshot(plane)
    plane.reshard(4)
    after = snapshot(plane)
    assert set(before) == set(after)
    for key in before:
        np.testing.assert_array_equal(before[key][0], after[key][0])
        np.testing.assert_array_equal(before[key][1], after[key][1])
    assert plane.stats()["spill_bytes"] >= 0
    plane.close()


# -- export / restore under the integrity chain -------------------------------


def test_save_restore_roundtrip_with_digest_chain(tmp_path):
    plane = drive(make_plane(2))
    plane.save(str(tmp_path), step=4)
    drive(plane, steps=2, seed=9)
    plane.save(str(tmp_path), step=6, delta=True)

    fresh = make_plane(2)
    assert fresh.restore(str(tmp_path)) == plane.step
    keys = np.arange(500, dtype=np.int64)
    np.testing.assert_array_equal(fresh.peek(keys), plane.peek(keys))
    assert snapshot(fresh).keys() == snapshot(plane).keys()
    plane.close()
    fresh.close()


def test_restore_into_resized_world_repartitions(tmp_path):
    """Cross-world restore: shards saved at world 4 land on a world-2
    plane re-partitioned by the CURRENT fold — same rows, new owners."""
    plane = drive(make_plane(4))
    plane.save(str(tmp_path), step=4)
    fresh = make_plane(2)
    fresh.restore(str(tmp_path))
    assert len(fresh) == len(plane)
    keys = np.arange(500, dtype=np.int64)
    np.testing.assert_array_equal(fresh.peek(keys), plane.peek(keys))
    for rank in range(2):
        owned = fresh._hosts[rank].export()[0]
        np.testing.assert_array_equal(
            fresh.owner_of(owned), np.full(owned.shape, rank)
        )
    plane.close()
    fresh.close()


def test_corrupt_export_falls_back_to_previous_full(tmp_path):
    plane = drive(make_plane(2), steps=2)
    plane.save(str(tmp_path), step=2)
    good = {k: v[0] for k, v in snapshot(plane).items()}
    drive(plane, steps=2, seed=5)
    plane.save(str(tmp_path), step=4)
    # Corrupt the newest full export's data leg: digest must reject it.
    newest = os.path.join(str(tmp_path), "plane_full_4")
    victim = next(
        os.path.join(newest, f) for f in sorted(os.listdir(newest))
        if f.endswith(".data")
    )
    with open(victim, "r+b") as f:
        f.seek(10)
        f.write(b"\xff\xff\xff")
    fresh = make_plane(2)
    fresh.restore(str(tmp_path))
    restored = {k: v[0] for k, v in snapshot(fresh).items()}
    assert set(restored) == set(good)
    for key in good:
        np.testing.assert_array_equal(restored[key], good[key])
    plane.close()
    fresh.close()


def test_corrupt_late_shard_never_mixes_two_checkpoints(tmp_path):
    """A digest mismatch on the LAST shard must reject the whole export
    before any row lands: restore is two-pass (verify all, then insert),
    so the fallback full is the only checkpoint the plane ever holds."""
    plane = drive(make_plane(2), steps=2)
    plane.save(str(tmp_path), step=2)
    good = snapshot(plane)
    drive(plane, steps=2, seed=5)
    plane.save(str(tmp_path), step=4)
    newest = os.path.join(str(tmp_path), "plane_full_4")
    victim = [
        os.path.join(newest, f) for f in sorted(os.listdir(newest))
        if f.endswith(".data")
    ][-1]  # the LAST shard read — earlier shards verify clean
    with open(victim, "r+b") as f:
        f.seek(10)
        f.write(b"\xff\xff\xff")
    fresh = make_plane(2)
    fresh.restore(str(tmp_path))
    restored = snapshot(fresh)
    assert set(restored) == set(good)
    for key in good:
        np.testing.assert_array_equal(restored[key][0], good[key][0])
        np.testing.assert_array_equal(restored[key][1], good[key][1])
    plane.close()
    fresh.close()


def test_torn_export_missing_shard_falls_back(tmp_path):
    """An export missing a host shard (interrupted save) is rejected for
    the previous full — rank completeness is part of verification."""
    plane = drive(make_plane(2), steps=2)
    plane.save(str(tmp_path), step=2)
    good = {k: v[0] for k, v in snapshot(plane).items()}
    drive(plane, steps=2, seed=5)
    plane.save(str(tmp_path), step=4)
    newest = os.path.join(str(tmp_path), "plane_full_4")
    for fname in os.listdir(newest):
        if fname.startswith("host_1_"):
            os.remove(os.path.join(newest, fname))
    fresh = make_plane(2)
    fresh.restore(str(tmp_path))
    restored = {k: v[0] for k, v in snapshot(fresh).items()}
    assert set(restored) == set(good)
    for key in good:
        np.testing.assert_array_equal(restored[key], good[key])
    plane.close()
    fresh.close()


def test_corrupt_delta_is_rejected_and_restore_continues(tmp_path):
    """A corrupt delta export loses its window but must not abort the
    restore or half-apply: the full export's state survives intact."""
    plane = drive(make_plane(2), steps=2)
    plane.save(str(tmp_path), step=2)
    good = snapshot(plane)
    drive(plane, steps=1, seed=8)
    plane.save(str(tmp_path), step=3, delta=True)
    delta_dir = os.path.join(str(tmp_path), "plane_delta_3")
    victim = next(
        os.path.join(delta_dir, f) for f in sorted(os.listdir(delta_dir))
        if f.endswith(".data")
    )
    with open(victim, "r+b") as f:
        f.seek(10)
        f.write(b"\xff\xff\xff")
    fresh = make_plane(2)
    fresh.restore(str(tmp_path))  # must not raise
    restored = snapshot(fresh)
    assert set(restored) == set(good)
    for key in good:
        np.testing.assert_array_equal(restored[key][0], good[key][0])
    plane.close()
    fresh.close()


def test_failed_save_keeps_the_delta_watermark(tmp_path):
    """A save that dies partway (storage.write fault) must not advance
    ``_last_export_step``: the next drain still covers every row touched
    since the last SUCCESSFUL export — the preemption-drain guarantee."""
    plane = drive(make_plane(2), steps=2)
    plane.save(str(tmp_path), step=2)
    watermark = plane._last_export_step
    drive(plane, steps=1, seed=8)
    faults.configure("storage.write:error@1")
    try:
        with pytest.raises(faults.FaultInjected):
            plane.save(str(tmp_path), step=3, delta=True)
    finally:
        faults.reset()
    assert plane._last_export_step == watermark
    out = plane.drain(str(tmp_path), step=4)
    assert "delta" in os.path.basename(out)
    fresh = make_plane(2)
    fresh.restore(str(tmp_path))
    keys = np.arange(500, dtype=np.int64)
    np.testing.assert_array_equal(fresh.peek(keys), plane.peek(keys))
    plane.close()
    fresh.close()


def test_drain_flushes_the_delta_leg(tmp_path):
    plane = drive(make_plane(2), steps=2)
    plane.save(str(tmp_path), step=2)
    drive(plane, steps=1, seed=8)
    out = plane.drain(str(tmp_path), step=3)
    assert "delta" in os.path.basename(out)
    fresh = make_plane(2)
    fresh.restore(str(tmp_path))
    keys = np.arange(500, dtype=np.int64)
    np.testing.assert_array_equal(fresh.peek(keys), plane.peek(keys))
    plane.close()
    fresh.close()


def test_booking_roundtrip_adopts_world_and_clocks():
    plane = drive(make_plane(4))
    booking = plane.booking()
    assert booking["world"] == 4 and booking["num_buckets"] == 16
    other = make_plane(2)
    other.adopt_booking(booking)
    assert other.world == 4
    assert other.step == plane.step
    mismatched = dict(booking, num_buckets=999)
    with pytest.raises(ValueError):
        other.adopt_booking(mismatched)
    plane.close()
    other.close()


def test_stats_and_telemetry_snapshot():
    plane = drive(make_plane(2))
    st = plane.stats()
    assert st["world"] == 2
    assert st["rows_owned"] == len(plane)
    assert st["lookups"] == 4 and st["rows_fetched"] > 0
    plane.reshard(4)
    st = plane.stats()
    assert st["reshards"] == 1 and st["moved_rows"] > 0
    assert st["reshard_s"] > 0.0
    plane.close()


# -- device hot-row cache -----------------------------------------------------


def make_cache(plane, capacity=64, max_unique=32):
    return DeviceHotRowCache(plane, capacity=capacity, max_unique=max_unique)


def test_cache_lookup_matches_plane_bitwise():
    plane = make_plane(2)
    cache = make_cache(plane)
    keys = np.array([[3, 7, 11], [7, 3, 19]], np.int64)
    rows, uniq, inverse = cache.lookup(keys)
    assert rows.shape == (32, DIM)
    np.testing.assert_array_equal(
        np.asarray(rows)[: len(uniq)], plane.peek(uniq)
    )
    flat_rows = np.asarray(rows)[inverse].reshape(2, 3, DIM)
    np.testing.assert_array_equal(
        flat_rows, plane.peek(keys).reshape(2, 3, DIM)
    )
    plane.close()


def test_cache_hits_and_misses_accounted():
    plane = make_plane(2)
    cache = make_cache(plane)
    cache.lookup(np.array([1, 2, 3], np.int64))
    assert cache.misses == 3 and cache.hits == 0
    cache.lookup(np.array([1, 2, 4], np.int64))
    assert cache.misses == 4 and cache.hits == 2
    assert cache.hit_rate == pytest.approx(2 / 6)
    plane.close()


def test_cache_evicts_lru_outside_current_batch():
    plane = make_plane(2)
    cache = make_cache(plane, capacity=5, max_unique=4)
    cache.lookup(np.array([1, 2, 3, 4], np.int64))
    cache.lookup(np.array([2, 3, 4], np.int64))  # 1 becomes LRU
    cache.lookup(np.array([5], np.int64))        # needs one slot
    assert cache.evictions == 1
    assert 1 not in cache and 5 in cache
    for key in (2, 3, 4):
        assert key in cache
    plane.close()


def test_cache_writeback_after_gradients_stays_bitwise():
    plane = make_plane(2)
    cache = make_cache(plane)
    keys = np.array([10, 20, 30], np.int64)
    _, uniq, _ = cache.lookup(keys)
    grads = np.ones((len(uniq), DIM), np.float32)
    cache.apply_gradients(uniq, grads)
    rows, _, _ = cache.lookup(keys)  # all hits — device copy must be fresh
    assert cache.misses == 3
    np.testing.assert_array_equal(
        np.asarray(rows)[: len(uniq)], plane.peek(uniq)
    )
    plane.close()


def test_cache_steady_state_does_not_retrace():
    plane = make_plane(2)
    cache = make_cache(plane)
    rng = np.random.default_rng(0)
    for _ in range(3):  # warmup: pays the two compilations
        cache.lookup(rng.integers(0, 300, size=16).astype(np.int64))
    with trace_asserts.assert_no_retrace("embed_gather", "embed_scatter"):
        for _ in range(5):  # varied unique counts, same padded shapes
            n = int(rng.integers(1, 30))
            cache.lookup(rng.integers(0, 300, size=n).astype(np.int64))
    plane.close()


def test_cache_rejects_oversized_batch_and_tiny_capacity():
    plane = make_plane(2)
    with pytest.raises(ValueError):
        DeviceHotRowCache(plane, capacity=8, max_unique=8)
    cache = make_cache(plane, capacity=9, max_unique=8)
    with pytest.raises(ValueError):
        cache.lookup(np.arange(9, dtype=np.int64))
    plane.close()


def test_cache_invalidate_drops_residency():
    plane = make_plane(2)
    cache = make_cache(plane)
    cache.lookup(np.array([1, 2], np.int64))
    cache.invalidate()
    assert len(cache) == 0
    cache.lookup(np.array([1, 2], np.int64))
    assert cache.misses == 4  # refetched after the invalidate
    plane.close()


def test_prefetcher_preserves_order_and_warms_cache():
    plane = make_plane(2)
    cache = make_cache(plane)
    batches = [
        {"ids": np.array([i, i + 100], np.int64), "tag": i}
        for i in range(5)
    ]
    pf = EmbeddingPrefetcher(iter(batches), cache, depth=2)
    seen = []
    for batch in pf:
        # Depth-2 prefetch keeps the NEXT batch resident before its turn.
        assert int(batch["ids"][0]) in cache
        seen.append(batch["tag"])
    assert seen == [0, 1, 2, 3, 4]
    assert cache.misses == 10  # every unique id warmed exactly once
    plane.close()


def test_prefetcher_drain_rewarms_after_invalidate():
    plane = make_plane(2)
    cache = make_cache(plane)
    batches = [
        {"ids": np.array([i, i + 100], np.int64)} for i in range(4)
    ]
    pf = EmbeddingPrefetcher(iter(batches), cache, depth=2)
    it = iter(pf)
    next(it)
    # A restore/reshard under the cache: residency gone, batches kept.
    cache.invalidate()
    assert pf.drain() > 0
    out = list(it)
    assert len(out) == 3
    assert all(int(b["ids"][0]) in cache for b in out)
    plane.close()


# -- kernels: pallas contract parity ------------------------------------------


def test_kernel_modes_resolve():
    assert kernels.kernel_mode() in ("pallas", "interpret", "jnp")


def test_pallas_interpret_matches_jnp_contract(monkeypatch):
    """The Pallas kernel body (run in interpreter mode on CPU) and the
    jnp fallback are the same function: same gather, same scatter, same
    aliasing semantics."""
    rng = np.random.default_rng(0)
    cache_host = rng.normal(size=(16, DIM)).astype(np.float32)
    slots = np.array([3, 0, 7, 7, 1], np.int32)
    # Duplicate scatter targets are only ever the scratch slot 0 carrying
    # identical (zero) padding rows — the contract the cache guarantees.
    scatter_slots = np.array([2, 5, 9, 0, 0], np.int32)
    rows = rng.normal(size=(5, DIM)).astype(np.float32)
    rows[3:] = 0.0

    monkeypatch.setenv(kernels.ENV_MODE, "jnp")
    got_jnp = np.asarray(
        kernels.gather_rows(jnp.asarray(cache_host), slots)
    )
    scat_jnp = np.asarray(kernels.scatter_rows(
        jnp.asarray(cache_host), scatter_slots, rows
    ))
    monkeypatch.setenv(kernels.ENV_MODE, "interpret")
    got_pl = np.asarray(
        kernels.gather_rows(jnp.asarray(cache_host), slots)
    )
    scat_pl = np.asarray(kernels.scatter_rows(
        jnp.asarray(cache_host), scatter_slots, rows
    ))
    np.testing.assert_array_equal(got_jnp, cache_host[slots])
    np.testing.assert_array_equal(got_pl, got_jnp)
    np.testing.assert_array_equal(scat_pl, scat_jnp)
