"""Sparse embedding engine (KvVariable equivalent): store semantics, group
Adam, delta export, checkpoint replay, native/python parity, and a
wide-and-deep toy trained end-to-end with elastic restart."""

import numpy as np
import optax
import pytest

import jax
import jax.numpy as jnp

from dlrover_tpu.embedding import EmbeddingTable, KVStore
from dlrover_tpu.embedding.store import _load_native


def stores():
    out = [KVStore(8, native=False)]
    if _load_native() is not None:
        out.append(KVStore(8, native=True))
    return out


def test_native_library_builds():
    assert _load_native() is not None, (
        "native kv_store failed to build — g++ is expected in this image"
    )


def test_lookup_inserts_deterministically_and_counts():
    for store in stores():
        keys = np.array([5, 9, 5], np.int64)
        rows = store.lookup(keys, init_scale=0.1, seed=7, step=1)
        assert rows.shape == (3, 8)
        np.testing.assert_array_equal(rows[0], rows[2])  # same key, same row
        assert np.abs(rows).max() <= 0.1
        assert len(store) == 2
        again = store.lookup(np.array([5], np.int64), 0.1, 7, step=2)
        np.testing.assert_array_equal(again[0], rows[0])
        _, _, _, _, counts, steps = store.export()
        assert sorted(counts.tolist()) == [1, 3]
        assert steps.max() == 2


def test_capacity_growth_beyond_initial():
    store = KVStore(4, initial_capacity=64)
    keys = np.arange(10_000, dtype=np.int64)
    store.lookup(keys, 0.05, 0, 1)
    assert len(store) == 10_000
    row = store.peek(np.array([1234], np.int64))
    assert np.abs(row).max() > 0  # row survived the rehashes


def test_group_adam_matches_optax_dense():
    """The in-store sparse Adam must match optax.adam on the same rows."""
    for store in stores():
        keys = np.array([3, 8], np.int64)
        rows = store.lookup(keys, 0.1, 1, 1)
        params = jnp.asarray(rows)
        opt = optax.adam(0.05, b1=0.9, b2=0.999, eps=1e-8)
        state = opt.init(params)
        rng = np.random.default_rng(0)
        for t in range(1, 4):
            grads = rng.normal(size=(2, 8)).astype(np.float32)
            updates, state = opt.update(jnp.asarray(grads), state, params)
            params = optax.apply_updates(params, updates)
            store.apply_group_adam(keys, grads, lr=0.05, t=t)
        np.testing.assert_allclose(
            store.peek(keys), np.asarray(params), rtol=1e-5, atol=1e-6
        )


def test_peek_does_not_insert():
    for store in stores():
        out = store.peek(np.array([42], np.int64))
        np.testing.assert_array_equal(out, 0.0)
        assert len(store) == 0


def test_delta_export_only_recent_keys():
    for store in stores():
        store.lookup(np.array([1, 2], np.int64), 0.1, 0, step=1)
        store.lookup(np.array([3], np.int64), 0.1, 0, step=5)
        keys_all, *_ = store.export(min_step=0)
        keys_delta, *_ = store.export(min_step=5)
        assert sorted(keys_all.tolist()) == [1, 2, 3]
        assert keys_delta.tolist() == [3]


def test_eviction_drops_cold_stale_features():
    for store in stores():
        store.lookup(np.array([1], np.int64), 0.1, 0, step=1)
        store.lookup(np.array([2], np.int64), 0.1, 0, step=10)
        evicted = store.evict(min_step=5, min_count=2)
        assert evicted == 1
        assert len(store) == 1
        assert store.peek(np.array([2], np.int64)).any()


def test_native_python_parity_full_flow():
    if _load_native() is None:
        pytest.skip("no native build")
    native = KVStore(8, native=True)
    pure = KVStore(8, native=False)
    keys = np.array([11, 22, 33], np.int64)
    rows_n = native.lookup(keys, 0.1, 3, 1)
    pure.insert(keys, rows_n)  # same starting rows (init RNGs differ)
    grads = np.random.default_rng(1).normal(size=(3, 8)).astype(np.float32)
    native.apply_group_adam(keys, grads, lr=0.1, t=1)
    pure.apply_group_adam(keys, grads, lr=0.1, t=1)
    np.testing.assert_allclose(
        native.peek(keys), pure.peek(keys), rtol=1e-5, atol=1e-6
    )


def test_table_checkpoint_full_plus_delta_replay(tmp_path):
    table = EmbeddingTable("emb", dim=8, learning_rate=0.1, seed=2)
    rows, uniq, inv = table.lookup(np.array([[1, 2], [3, 1]], np.int64))
    assert rows.shape == (3, 8) and inv.shape == (4,)
    table.apply_gradients(uniq, np.ones((3, 8), np.float32))
    table.save(str(tmp_path), step=1)
    # More training -> delta with only the newly-touched key.
    rows2, uniq2, _ = table.lookup(np.array([7], np.int64))
    table.apply_gradients(uniq2, np.ones((1, 8), np.float32))
    table.save(str(tmp_path), step=2, delta=True)

    fresh = EmbeddingTable("emb", dim=8, learning_rate=0.1, seed=2)
    fresh.restore(str(tmp_path))
    assert len(fresh) == 4
    np.testing.assert_allclose(
        fresh.store.peek(np.array([1, 2, 3, 7], np.int64)),
        table.store.peek(np.array([1, 2, 3, 7], np.int64)),
        rtol=1e-6,
    )


def test_native_build_retries_once_before_latching(monkeypatch, tmp_path):
    """A transient compiler failure must not permanently demote the
    process to the NumPy fallback: the first failed build leaves the
    latch open, the next ``_load_native`` retries and succeeds, and only
    two consecutive failures set ``_lib_failed``."""
    import subprocess as real_subprocess

    from dlrover_tpu.embedding import store

    real_run = real_subprocess.run
    calls = {"n": 0}

    def flaky_run(cmd, **kw):
        calls["n"] += 1
        if calls["n"] == 1:
            raise real_subprocess.CalledProcessError(
                1, cmd, stderr="cc1plus: out of memory"
            )
        return real_run(cmd, **kw)

    # Fresh module state pointed at a lib path that forces a build.
    monkeypatch.setattr(store, "_LIB", str(tmp_path / "libkvstore.so"))
    monkeypatch.setattr(store, "_lib", None)
    monkeypatch.setattr(store, "_lib_failed", False)
    monkeypatch.setattr(store, "_build_attempts", 0)
    monkeypatch.setattr(store.subprocess, "run", flaky_run)

    assert store._load_native() is None      # first build fails...
    assert store._lib_failed is False        # ...but does NOT latch
    lib = store._load_native()               # retry rebuilds for real
    assert lib is not None and calls["n"] == 2
    assert store._load_native() is lib       # cached, no third build


def test_native_build_latches_after_two_failures(monkeypatch, tmp_path):
    import subprocess as real_subprocess

    from dlrover_tpu.embedding import store

    def always_fail(cmd, **kw):
        raise real_subprocess.CalledProcessError(1, cmd, stderr="boom")

    monkeypatch.setattr(store, "_LIB", str(tmp_path / "libkvstore.so"))
    monkeypatch.setattr(store, "_lib", None)
    monkeypatch.setattr(store, "_lib_failed", False)
    monkeypatch.setattr(store, "_build_attempts", 0)
    monkeypatch.setattr(store.subprocess, "run", always_fail)

    assert store._load_native() is None
    assert store._lib_failed is False
    assert store._load_native() is None
    assert store._lib_failed is True         # second failure latches
    # Latched: further calls return immediately without building.
    assert store._load_native() is None
    # The fallback store still works under the latch.
    fallback = KVStore(4)
    assert fallback.native is False
    fallback.lookup(np.array([1], np.int64), 0.1, 0, 1)
    assert len(fallback) == 1


def test_store_remove_deletes_keys_both_backends():
    """Targeted deletion (the reshard migration's remove leg): removed
    keys vanish, survivors keep their rows — including keys that shared
    a probe chain with the victim (backward-shift correctness)."""
    for store in stores():
        keys = np.arange(64, dtype=np.int64)
        before = store.lookup(keys, 0.1, 5, 1)
        removed = store.remove(np.array([3, 9, 63, 777], np.int64))
        assert removed == 3  # 777 was never inserted
        assert len(store) == 61
        np.testing.assert_array_equal(
            store.peek(np.array([3, 9, 63], np.int64)), 0.0
        )
        survivors = np.array(
            [k for k in range(64) if k not in (3, 9, 63)], np.int64
        )
        np.testing.assert_array_equal(
            store.peek(survivors), before[survivors]
        )


def test_wide_and_deep_toy_trains_with_restart(tmp_path):
    """End-to-end recsys slice: sparse table + dense tower trained jointly;
    kill mid-run, restore both halves, loss keeps falling (the verdict's
    'wide-and-deep toy trains with elastic restart')."""
    rng = np.random.default_rng(0)
    n_features, dim = 50, 8

    def make_batch():
        feats = rng.integers(0, n_features, size=(16, 3)).astype(np.int64)
        # Ground truth depends on feature identity: learnable signal.
        label = ((feats.sum(axis=1) % 7) / 7.0).astype(np.float32)
        return feats, label

    def dense_apply(w, emb_rows, inv, feats_shape):
        gathered = emb_rows[inv].reshape(*feats_shape, dim)
        pooled = gathered.mean(axis=1)
        return (pooled @ w).squeeze(-1)

    from functools import partial

    @partial(jax.jit, static_argnums=(4, 5))
    def step_fn(w, emb_rows, inv, label, shape0, shape1):
        def loss_fn(w, emb_rows):
            pred = dense_apply(w, emb_rows, inv, (shape0, shape1))
            return jnp.mean((pred - label) ** 2)

        loss, (dw, drows) = jax.value_and_grad(loss_fn, argnums=(0, 1))(
            w, emb_rows
        )
        return loss, dw, drows

    def train(table, w, steps, opt_state, opt):
        losses = []
        for _ in range(steps):
            feats, label = make_batch()
            rows, uniq, inv = table.lookup(feats)
            loss, dw, drows = step_fn(
                w, jnp.asarray(rows), jnp.asarray(inv),
                jnp.asarray(label), *feats.shape,
            )
            updates, opt_state = opt.update(dw, opt_state, w)
            w = optax.apply_updates(w, updates)
            table.apply_gradients(uniq, np.asarray(drows))
            losses.append(float(loss))
        return w, opt_state, losses

    table = EmbeddingTable("wd", dim=dim, learning_rate=0.05, seed=1)
    w = jnp.zeros((dim, 1), jnp.float32)
    opt = optax.adam(0.05)
    opt_state = opt.init(w)
    w, opt_state, losses1 = train(table, w, 30, opt_state, opt)
    table.save(str(tmp_path), step=30)
    np.save(tmp_path / "w.npy", np.asarray(w))

    # "Crash": rebuild everything from the checkpoint, keep training.
    table2 = EmbeddingTable("wd", dim=dim, learning_rate=0.05, seed=1)
    table2.restore(str(tmp_path))
    assert len(table2) == len(table)
    w2 = jnp.asarray(np.load(tmp_path / "w.npy"))
    opt_state2 = opt.init(w2)
    _, _, losses2 = train(table2, w2, 30, opt_state2, opt)
    assert np.mean(losses2[-5:]) < np.mean(losses1[:5]), (
        "loss did not improve across the restart"
    )
