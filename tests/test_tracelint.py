"""Unit tests for the tracelint static-analysis framework.

Every rule gets a positive fixture (a distilled version of the incident
that motivated it) and a negative fixture (the sanctioned spelling of the
same pattern), plus round-trips for inline suppressions, the baseline
file, and the CLI exit-code contract.  Fixtures are analyzed in-process
via ``run_paths`` — no subprocess per case — so the whole module stays
fast; the CLI itself is exercised once at the end and by
``tests/test_lint_gate.py``.
"""

import json
import os
import subprocess
import sys

import pytest

from dlrover_tpu.analysis import (
    all_rules,
    load_baseline,
    run_paths,
    write_baseline,
)
from dlrover_tpu.analysis.engine import (
    EXIT_CLEAN,
    EXIT_ERROR,
    EXIT_FINDINGS,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

ALL_RULE_IDS = {
    "TRC001", "TRC002", "TRC003", "CMP001", "THR001", "LOG001", "RTY001",
    "DON001", "DON002", "SHD001", "SHD002", "SEAM001",
    "CKY001", "TEL001", "LCK001",
}


def lint(tmp_path, name, source, select=None, baseline=None):
    """Write ``source`` under ``tmp_path`` and analyze just that file."""
    path = tmp_path / name
    path.write_text(source)
    return run_paths(
        [str(path)], select=select, baseline=baseline, root=str(tmp_path)
    )


def lint_files(tmp_path, files, select=None, baseline=None):
    """Write a whole fixture tree and analyze it — the project-scope
    rules (CKY001/TEL001) need several modules linked by imports."""
    for name, source in files.items():
        path = tmp_path / name
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(source)
    return run_paths(
        [str(tmp_path)], select=select, baseline=baseline,
        root=str(tmp_path),
    )


def rule_ids(report):
    return sorted({f.rule for f in report.findings})


def test_all_rules_registered():
    assert {r.id for r in all_rules()} == ALL_RULE_IDS


# -- TRC001: flax module construction inside a scan-like body --------------

TRC001_BAD = """\
import flax.linen as nn
import jax

def outer(params, xs):
    def body(carry, x):
        proj = nn.Dense(4)
        return carry, proj(x)
    return jax.lax.scan(body, params, xs)
"""

# Construction under jit (outside scan bodies) is the standard linen
# idiom — module __init__ is metadata-only there.
TRC001_OK = """\
import flax.linen as nn
import jax

@jax.jit
def apply(params, x):
    model = nn.Dense(4)
    return model.apply(params, x)
"""


def test_trc001_fires_on_module_in_scan_body(tmp_path):
    report = lint(tmp_path, "m.py", TRC001_BAD)
    assert rule_ids(report) == ["TRC001"]
    assert "nn.Dense" in report.findings[0].message


def test_trc001_allows_module_under_jit(tmp_path):
    report = lint(tmp_path, "m.py", TRC001_OK, select=["TRC001"])
    assert report.findings == []


# -- TRC002: host sync on the hot step path --------------------------------

TRC002_BAD = """\
import jax

class Trainer:
    def fit(self, batches):
        for batch in batches:
            out = self.step(batch)
            loss = float(out)
            host = jax.device_get(out)
        return loss
"""

TRC002_OK = """\
import jax

class Trainer:
    def fit(self, batches):
        for batch in batches:
            out = self.step(batch)
        with pipeline_counters().host_block("metrics_flush"):
            host = jax.device_get(out)
        return host
"""


def test_trc002_fires_in_hot_file(tmp_path):
    report = lint(tmp_path, "elastic_trainer.py", TRC002_BAD)
    assert rule_ids(report) == ["TRC002"]
    assert len(report.findings) == 2  # float(out) + device_get


def test_trc002_sanctioned_host_block(tmp_path):
    report = lint(tmp_path, "elastic_trainer.py", TRC002_OK)
    assert report.findings == []


def test_trc002_ignores_cold_files(tmp_path):
    report = lint(tmp_path, "not_hot.py", TRC002_BAD)
    assert report.findings == []


# -- TRC003: host impurity inside traced code ------------------------------

TRC003_BAD = """\
import time
import jax

@jax.jit
def step(x):
    return x * time.time()
"""

TRC003_OK = """\
import time

def wall_clock():
    return time.time()
"""


def test_trc003_fires_inside_traced_fn(tmp_path):
    report = lint(tmp_path, "m.py", TRC003_BAD)
    assert rule_ids(report) == ["TRC003"]
    assert "time.time" in report.findings[0].message


def test_trc003_allows_host_side_clock(tmp_path):
    report = lint(tmp_path, "m.py", TRC003_OK, select=["TRC003"])
    assert report.findings == []


# -- CMP001: version-gated APIs without the compat shim --------------------

CMP001_BAD = """\
import tomllib
import jax

def activate(mesh):
    jax.set_mesh(mesh)
"""

CMP001_OK = """\
try:
    import tomllib
except ImportError:
    tomllib = None
import jax

def activate(mesh):
    if hasattr(jax, "set_mesh"):
        jax.set_mesh(mesh)
"""


def test_cmp001_fires_on_ungated_uses(tmp_path):
    report = lint(tmp_path, "m.py", CMP001_BAD)
    assert rule_ids(report) == ["CMP001"]
    symbols = {f.symbol for f in report.findings}
    assert symbols == {"import:tomllib", "jax.set_mesh"}


def test_cmp001_allows_probed_uses(tmp_path):
    report = lint(tmp_path, "m.py", CMP001_OK, select=["CMP001"])
    assert report.findings == []


def test_cmp001_exempts_the_shim_module(tmp_path):
    report = lint(tmp_path, "mesh.py", CMP001_BAD, select=["CMP001"])
    # The shim file may touch gated JAX names; the tomllib import gate
    # still applies everywhere.
    assert {f.symbol for f in report.findings} == {"import:tomllib"}


# -- THR001: cross-thread attribute without a lock -------------------------

THR001_BAD = """\
import threading

class Pump:
    def __init__(self):
        self.count = 0
        self._thread = None

    def start(self):
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        while True:
            self.count += 1

    def snapshot(self):
        return self.count
"""

THR001_OK = """\
import threading

class Pump:
    def __init__(self):
        self.count = 0
        self._lock = threading.Lock()
        self._thread = None

    def start(self):
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        while True:
            with self._lock:
                self.count += 1

    def snapshot(self):
        return self.count
"""

THR001_QUEUE_OK = """\
import multiprocessing as mp
import threading

class Feeder:
    def _start(self):
        ctx = mp.get_context("spawn")
        self._task_queue = ctx.Queue(maxsize=4)
        threading.Thread(target=self._feed, daemon=True).start()

    def _feed(self):
        while True:
            self._task_queue.put(1)
"""


def test_thr001_fires_on_unlocked_cross_thread_write(tmp_path):
    report = lint(tmp_path, "m.py", THR001_BAD)
    assert rule_ids(report) == ["THR001"]
    assert report.findings[0].symbol == "Pump.count"


def test_thr001_locked_write_is_clean(tmp_path):
    report = lint(tmp_path, "m.py", THR001_OK, select=["THR001"])
    assert report.findings == []


def test_thr001_mp_queue_attr_is_threadsafe(tmp_path):
    report = lint(tmp_path, "m.py", THR001_QUEUE_OK, select=["THR001"])
    assert report.findings == []


# -- LOG001: eagerly formatted logging -------------------------------------

LOG001_BAD = """\
import logging

logger = logging.getLogger(__name__)

def report(step, loss):
    logger.info(f"step {step}")
    logger.warning("loss %s" % loss)
    logger.error("msg: {}".format(step))
"""

LOG001_OK = """\
import logging

logger = logging.getLogger(__name__)

def report(step, loss):
    logger.info("step %d loss %.3f", step, loss)
"""


def test_log001_fires_on_eager_formats(tmp_path):
    report = lint(tmp_path, "m.py", LOG001_BAD)
    assert rule_ids(report) == ["LOG001"]
    assert len(report.findings) == 3


def test_log001_lazy_template_is_clean(tmp_path):
    report = lint(tmp_path, "m.py", LOG001_OK, select=["LOG001"])
    assert report.findings == []


# -- RTY001: hand-rolled retry loops + silent swallows ---------------------

RTY001_RETRY_LOOP = """\
import time

def fetch(url):
    for attempt in range(5):
        try:
            return do_fetch(url)
        except ConnectionError:
            time.sleep(2 ** attempt)
    raise RuntimeError("gave up")
"""

# The sanctioned spelling: no sleep in the handler, the policy owns it.
RTY001_OK_POLICY = """\
from dlrover_tpu.common.retry import RetryPolicy

def fetch(url):
    return RetryPolicy(max_attempts=5).call(do_fetch, url)
"""

# A poll loop that sleeps OUTSIDE the except handler is not a retry loop.
RTY001_OK_POLL = """\
import time

def watch(poll):
    while True:
        try:
            poll()
        except StopIteration:
            break
        time.sleep(1.0)
"""

RTY001_SWALLOW = """\
def shutdown(client):
    try:
        client.close()
    except Exception:
        pass
"""


def test_rty001_fires_on_catch_sleep_retry_loop(tmp_path):
    report = lint(tmp_path, "m.py", RTY001_RETRY_LOOP, select=["RTY001"])
    assert rule_ids(report) == ["RTY001"]
    assert "RetryPolicy" in report.findings[0].message


def test_rty001_policy_call_and_poll_loop_are_clean(tmp_path):
    for src in (RTY001_OK_POLICY, RTY001_OK_POLL):
        report = lint(tmp_path, "m.py", src, select=["RTY001"])
        assert report.findings == []


def test_rty001_retry_home_module_is_exempt(tmp_path):
    (tmp_path / "common").mkdir()
    report = lint(
        tmp_path, os.path.join("common", "retry.py"),
        RTY001_RETRY_LOOP, select=["RTY001"],
    )
    assert report.findings == []


def test_rty001_swallow_fires_only_in_failure_tiers(tmp_path):
    (tmp_path / "agent").mkdir()
    report = lint(
        tmp_path, os.path.join("agent", "m.py"),
        RTY001_SWALLOW, select=["RTY001"],
    )
    assert rule_ids(report) == ["RTY001"]
    # The same code outside agent/master/checkpoint is tolerated.
    report = lint(tmp_path, "util.py", RTY001_SWALLOW, select=["RTY001"])
    assert report.findings == []


# -- DON001: use-after-donate ----------------------------------------------

DON001_BAD = """\
import jax

step = jax.jit(train_step, donate_argnums=(0,))

def fit(state, batches):
    for batch in batches:
        out = step(state, batch)
    return state.params
"""

# The serving donated-pool idiom: the KV pool is donated to insert and
# the result is rebound over the operand in the same statement — the
# stale binding dies with the statement, so the pattern is clean.
DON001_OK_POOL = """\
import jax

class Engine:
    def __init__(self, fn):
        self._insert = jax.jit(fn, donate_argnums=(0,))

    def admit(self, pool, rows):
        for row, slot in rows:
            pool = self._insert(pool, row, slot)
        return pool

    def admit_cached(self, row, slot):
        self.cache = self._insert(self.cache, row, slot)
        return self.cache
"""

# AOT lowering reads shapes only; .lower on the jitted callable does not
# consume the buffer.
DON001_OK_AOT = """\
import jax

class Engine:
    def __init__(self, fn):
        self._insert = jax.jit(fn, donate_argnums=(0,))

    def warm(self, pool, row, slot):
        lowered = self._insert.lower(pool, row, slot)
        return lowered.compile(), pool
"""


def test_don001_fires_on_read_after_donate(tmp_path):
    report = lint(tmp_path, "m.py", DON001_BAD, select=["DON001"])
    assert rule_ids(report) == ["DON001"]
    finding = report.findings[0]
    assert "'state'" in finding.message
    assert finding.symbol == "fit:state"


def test_don001_branch_read_fires(tmp_path):
    src = """\
import jax

step = jax.jit(f, donate_argnums=(0,))

def g(state, flag):
    out = step(state, 1)
    if flag:
        return state
    return out
"""
    report = lint(tmp_path, "m.py", src, select=["DON001"])
    assert rule_ids(report) == ["DON001"]


def test_don001_donate_argnames_fires(tmp_path):
    src = """\
import jax

step = jax.jit(f, donate_argnames=("state",))

def g(s):
    out = step(state=s)
    return s
"""
    report = lint(tmp_path, "m.py", src, select=["DON001"])
    assert rule_ids(report) == ["DON001"]


def test_don001_serving_pool_idiom_is_clean(tmp_path):
    for src in (DON001_OK_POOL, DON001_OK_AOT):
        report = lint(tmp_path, "m.py", src, select=["DON001"])
        assert report.findings == []


def test_don001_conditional_donation_fires(tmp_path):
    # train_lib's "(0,) if donate_state else ()" spelling still donates
    # on some configuration — lint treats it as donating.
    src = """\
import jax

step = jax.jit(f, donate_argnums=(0,) if DONATE else ())

def g(state):
    out = step(state, 1)
    return state.params, out
"""
    report = lint(tmp_path, "m.py", src, select=["DON001"])
    assert rule_ids(report) == ["DON001"]


# -- DON002: donated binding captured by a closure -------------------------

DON002_BAD = """\
import jax

step = jax.jit(f, donate_argnums=(0,))

def outer(state):
    def peek():
        return state.params
    out = step(state, 1)
    return out, peek
"""

DON002_OK_REBOUND = """\
import jax

step = jax.jit(f, donate_argnums=(0,))

def outer(state):
    def peek():
        return state.params
    state = step(state, 1)
    return state, peek
"""


def test_don002_fires_on_closure_capture(tmp_path):
    report = lint(tmp_path, "m.py", DON002_BAD, select=["DON002"])
    assert rule_ids(report) == ["DON002"]
    assert "closure" in report.findings[0].message


def test_don002_rebound_operand_is_clean(tmp_path):
    report = lint(tmp_path, "m.py", DON002_OK_REBOUND, select=["DON002"])
    assert report.findings == []


# -- SHD001: PartitionSpec axis drift --------------------------------------

SHD001_BAD = """\
from jax.sharding import PartitionSpec as P

SPEC = P("dp", None)
"""

SHD001_OK_CANONICAL = """\
from jax.sharding import PartitionSpec as P

SPEC = P(("data", "fsdp"), None)
"""

SHD001_OK_LOCAL_MESH = """\
from jax.sharding import Mesh, PartitionSpec as P

mesh = Mesh(devices, ("rows", "cols"))
SPEC = P("rows")
"""


def test_shd001_fires_on_unknown_axis(tmp_path):
    report = lint(tmp_path, "m.py", SHD001_BAD, select=["SHD001"])
    assert rule_ids(report) == ["SHD001"]
    assert report.findings[0].symbol == "axis:dp"


def test_shd001_canonical_and_local_mesh_axes_are_clean(tmp_path):
    for src in (SHD001_OK_CANONICAL, SHD001_OK_LOCAL_MESH):
        report = lint(tmp_path, "m.py", src, select=["SHD001"])
        assert report.findings == []


def test_shd001_resolves_module_constants(tmp_path):
    src = """\
from jax.sharding import PartitionSpec as P

ROW_AXIS = "tesnor"
SPEC = P(ROW_AXIS)
"""
    report = lint(tmp_path, "m.py", src, select=["SHD001"])
    assert rule_ids(report) == ["SHD001"]
    assert report.findings[0].symbol == "axis:tesnor"


# -- SHD002: spec rank exceeds the array's known rank ----------------------

SHD002_BAD = """\
import jax.numpy as jnp
from jax.lax import with_sharding_constraint
from jax.sharding import PartitionSpec as P

def f():
    x = jnp.zeros((4, 8))
    x = with_sharding_constraint(x, P("data", "fsdp", "tensor"))
    return x
"""

SHD002_OK = """\
import jax.numpy as jnp
from jax.lax import with_sharding_constraint
from jax.sharding import PartitionSpec as P

def f():
    x = jnp.zeros((4, 8))
    x = with_sharding_constraint(x, P("data", "fsdp"))
    return x
"""


def test_shd002_fires_on_rank_overflow(tmp_path):
    report = lint(tmp_path, "m.py", SHD002_BAD, select=["SHD002"])
    assert rule_ids(report) == ["SHD002"]
    assert "rank 2" in report.findings[0].message


def test_shd002_matching_rank_and_unknown_rank_are_clean(tmp_path):
    report = lint(tmp_path, "m.py", SHD002_OK, select=["SHD002"])
    assert report.findings == []
    # Rank not statically derivable (function argument): stay silent.
    unknown = SHD002_OK.replace("x = jnp.zeros((4, 8))", "x = get()")
    report = lint(tmp_path, "m.py", unknown, select=["SHD002"])
    assert report.findings == []


# -- SEAM001: raw I/O outside Faultline ------------------------------------

SEAM001_BAD = """\
import os

def persist(path, blob):
    tmp = path + ".tmp"
    with open(tmp, "wb") as fh:
        fh.write(blob)
    os.replace(tmp, path)
"""

SEAM001_OK = """\
import os
from dlrover_tpu.common import faults

def persist(path, blob):
    faults.fire("storage.write", path=path)
    tmp = path + ".tmp"
    with open(tmp, "wb") as fh:
        fh.write(blob)
    os.replace(tmp, path)
"""

SEAM001_UNREGISTERED = """\
import os
from dlrover_tpu.common import faults

def persist(path, blob):
    faults.fire("made.up.seam")
    os.replace(path + ".tmp", path)
"""


def test_seam001_fires_in_fault_tiers(tmp_path):
    (tmp_path / "agent").mkdir()
    report = lint(
        tmp_path, os.path.join("agent", "m.py"),
        SEAM001_BAD, select=["SEAM001"],
    )
    assert rule_ids(report) == ["SEAM001"]
    kinds = {f.symbol for f in report.findings}
    assert kinds == {"persist:open-for-write", "persist:os.replace"}


def test_seam001_registered_seam_covers_the_function(tmp_path):
    (tmp_path / "checkpoint").mkdir()
    report = lint(
        tmp_path, os.path.join("checkpoint", "m.py"),
        SEAM001_OK, select=["SEAM001"],
    )
    assert report.findings == []


def test_seam001_unregistered_seam_does_not_count(tmp_path):
    (tmp_path / "master").mkdir()
    report = lint(
        tmp_path, os.path.join("master", "m.py"),
        SEAM001_UNREGISTERED, select=["SEAM001"],
    )
    assert rule_ids(report) == ["SEAM001"]


def test_seam001_ignores_cold_tiers(tmp_path):
    report = lint(tmp_path, "serving.py", SEAM001_BAD, select=["SEAM001"])
    assert report.findings == []


def test_seam001_covers_the_embedding_tier(tmp_path):
    """embedding/ is a fault tier: its spill logs and table exports are
    remote-storage-shaped I/O, so raw open/replace without a registered
    seam in scope is a finding — and the embed seams count as coverage."""
    (tmp_path / "embedding").mkdir()
    report = lint(
        tmp_path, os.path.join("embedding", "m.py"),
        SEAM001_BAD, select=["SEAM001"],
    )
    assert rule_ids(report) == ["SEAM001"]
    covered = SEAM001_BAD.replace(
        "def persist(path, blob):",
        "from dlrover_tpu.common import faults\n"
        "def persist(path, blob):\n"
        '    faults.fire("embed.reshard", src=2, dst=4)',
    )
    report = lint(
        tmp_path, os.path.join("embedding", "m2.py"),
        covered, select=["SEAM001"],
    )
    assert report.findings == []


SEAM001_READ_BAD = """\
def load(path):
    with open(path) as fh:
        return fh.read()
"""

SEAM001_READ_OK = """\
from dlrover_tpu.common import faults

def load(path):
    faults.fire("storage.read", path=path)
    with open(path) as fh:
        return fh.read()
"""


def test_seam001_flags_uncovered_reads_in_fault_tiers(tmp_path):
    """A read that silently swallows I/O errors is exactly the path a
    storage drill needs to reach — uncovered ``open``-for-read in a fault
    tier fires, and a ``storage.read`` seam covers it."""
    (tmp_path / "data").mkdir()
    report = lint(
        tmp_path, os.path.join("data", "m.py"),
        SEAM001_READ_BAD, select=["SEAM001"],
    )
    assert rule_ids(report) == ["SEAM001"]
    assert {f.symbol for f in report.findings} == {"load:open-for-read"}
    report = lint(
        tmp_path, os.path.join("data", "ok.py"),
        SEAM001_READ_OK, select=["SEAM001"],
    )
    assert report.findings == []


def test_seam001_proc_reads_are_exempt(tmp_path):
    """/proc pseudo-files are kernel state, not storage: no seam owed."""
    (tmp_path / "agent").mkdir()
    proc_only = """\
def cpu_times():
    with open("/proc/stat") as fh:
        return fh.read()
"""
    report = lint(
        tmp_path, os.path.join("agent", "m.py"),
        proc_only, select=["SEAM001"],
    )
    assert report.findings == []


# -- suppressions ----------------------------------------------------------

def test_inline_suppression_silences_one_rule(tmp_path):
    src = TRC003_BAD.replace(
        "return x * time.time()",
        "return x * time.time()  # tracelint: disable=TRC003",
    )
    report = lint(tmp_path, "m.py", src)
    assert report.findings == []
    assert report.suppressed == 1


def test_inline_suppression_disable_all(tmp_path):
    src = CMP001_BAD.replace(
        "jax.set_mesh(mesh)",
        "jax.set_mesh(mesh)  # tracelint: disable=all",
    )
    report = lint(tmp_path, "m.py", src, select=["CMP001"])
    assert {f.symbol for f in report.findings} == {"import:tomllib"}
    assert report.suppressed == 1


def test_suppression_for_other_rule_does_not_silence(tmp_path):
    src = TRC003_BAD.replace(
        "return x * time.time()",
        "return x * time.time()  # tracelint: disable=LOG001",
    )
    report = lint(tmp_path, "m.py", src)
    assert rule_ids(report) == ["TRC003"]
    assert report.suppressed == 0


# -- baseline --------------------------------------------------------------

def test_baseline_round_trip(tmp_path):
    report = lint(tmp_path, "m.py", CMP001_BAD)
    assert len(report.findings) == 2

    baseline_path = tmp_path / "baseline.json"
    write_baseline(str(baseline_path), report.findings)
    baseline = load_baseline(str(baseline_path))
    assert len(baseline) == 2

    again = lint(tmp_path, "m.py", CMP001_BAD, baseline=baseline)
    assert again.findings == []
    assert again.baselined == 2


def test_baseline_survives_line_drift(tmp_path):
    report = lint(tmp_path, "m.py", CMP001_BAD)
    baseline_path = tmp_path / "baseline.json"
    write_baseline(str(baseline_path), report.findings)
    baseline = load_baseline(str(baseline_path))

    drifted = "'''module docstring'''\n\n\n" + CMP001_BAD
    again = lint(tmp_path, "m.py", drifted, baseline=baseline)
    assert again.findings == []
    assert again.baselined == 2


# -- engine edge cases -----------------------------------------------------

def test_syntax_error_is_a_finding_not_a_crash(tmp_path):
    report = lint(tmp_path, "m.py", "def broken(:\n")
    assert rule_ids(report) == ["ENGINE"]
    assert report.exit_code == EXIT_FINDINGS


def test_unknown_rule_select_raises(tmp_path):
    (tmp_path / "m.py").write_text("x = 1\n")
    with pytest.raises(KeyError):
        run_paths([str(tmp_path)], select=["NOPE99"])


def test_findings_sorted_and_keyed(tmp_path):
    report = lint(tmp_path, "m.py", CMP001_BAD + "\n" + LOG001_BAD)
    keys = [(f.path, f.line, f.col, f.rule) for f in report.findings]
    assert keys == sorted(keys)
    for finding in report.findings:
        assert finding.baseline_key.startswith(f"{finding.rule}::m.py::")


# -- CLI exit codes --------------------------------------------------------

def _run_cli(args, env):
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "tracelint.py"),
         *args],
        capture_output=True, text=True, timeout=120, env=env,
    )


def test_cli_exit_codes_and_json(tmp_path, cpu_child_env):
    bad = tmp_path / "bad"
    bad.mkdir()
    (bad / "m.py").write_text(TRC003_BAD)
    good = tmp_path / "good"
    good.mkdir()
    (good / "m.py").write_text(TRC003_OK)

    dirty = _run_cli(
        [str(bad), "--root", str(bad), "--no-baseline", "--json"],
        cpu_child_env,
    )
    assert dirty.returncode == EXIT_FINDINGS, dirty.stderr
    payload = json.loads(dirty.stdout)
    assert payload["counts"] == {"TRC003": 1}
    assert payload["findings"][0]["rule"] == "TRC003"

    clean = _run_cli(
        [str(good), "--root", str(good), "--no-baseline"], cpu_child_env
    )
    assert clean.returncode == EXIT_CLEAN, clean.stderr

    usage = _run_cli(
        [str(good), "--select", "NOPE99", "--no-baseline"], cpu_child_env
    )
    assert usage.returncode == EXIT_ERROR


def test_cli_write_baseline_round_trip(tmp_path, cpu_child_env):
    (tmp_path / "m.py").write_text(CMP001_BAD)
    baseline = tmp_path / "base.json"

    wrote = _run_cli(
        [str(tmp_path), "--root", str(tmp_path), "--baseline",
         str(baseline), "--write-baseline"],
        cpu_child_env,
    )
    assert wrote.returncode == 0, wrote.stderr
    assert baseline.exists()

    clean = _run_cli(
        [str(tmp_path), "--root", str(tmp_path), "--baseline",
         str(baseline)],
        cpu_child_env,
    )
    assert clean.returncode == EXIT_CLEAN, clean.stdout


# -- CKY001: cache-key coverage (project scope) ----------------------------

CKY_KEYS = """\
def train_cache_key(model_config, mesh_shape, *, global_batch_size,
                    seq_len, zero1=False):
    fields = tuple(sorted(
        (k, repr(v)) for k, v in vars(model_config).items()
    ))
    return repr((fields, tuple(mesh_shape), global_batch_size, seq_len,
                 zero1))
"""

CKY_BUILD_OK = """\
from pkg.keys import train_cache_key

def build_sharded_train(model, mesh, *, global_batch_size, seq_len,
                        zero1=False, cache_key=None):
    key = cache_key or train_cache_key(
        model.config, mesh.shape, global_batch_size=global_batch_size,
        seq_len=seq_len, zero1=zero1,
    )
    return key
"""

# ``overlap`` shapes the program (a build-entry parameter) but is absent
# from train_cache_key's signature — the PR-19 aliasing shape.
CKY_BUILD_PARITY_BAD = """\
from pkg.keys import train_cache_key

def build_sharded_train(model, mesh, *, global_batch_size, seq_len,
                        zero1=False, overlap=False, cache_key=None):
    key = cache_key or train_cache_key(
        model.config, mesh.shape, global_batch_size=global_batch_size,
        seq_len=seq_len, zero1=zero1,
    )
    return key, overlap
"""

# A build-path function reads config.overlap — a knob the build entry
# names but the key does not — outside any key-call argument.
CKY_READ_BAD = """\
from pkg.build import build_sharded_train

def make_programs(config, model, mesh):
    overlap = config.overlap
    return build_sharded_train(
        model, mesh, global_batch_size=8, seq_len=16,
    ), overlap
"""

CKY_READ_SUPPRESSED = """\
from pkg.build import build_sharded_train

def make_programs(config, model, mesh):
    overlap = config.overlap  # tracelint: disable=CKY001
    return build_sharded_train(
        model, mesh, global_batch_size=8, seq_len=16,
    ), overlap
"""

# Sanctioned spellings: the read rides a key call's arguments, or the
# carrier goes into the key-reaching call whole.
CKY_READ_OK = """\
from pkg.keys import train_cache_key

def name_program(config, model_config, mesh):
    return train_cache_key(
        model_config, mesh.shape, global_batch_size=8, seq_len=16,
        zero1=config.zero1,
    )

def wrap_key(model_config, mesh):
    return train_cache_key(
        model_config, mesh.shape, global_batch_size=8, seq_len=16,
    )

def fold_whole(model_config, mesh):
    hidden = model_config.seq_len
    return wrap_key(model_config, mesh), hidden
"""

CKY_KEYS_NO_VARS = """\
def train_cache_key(model_config, mesh_shape, *, global_batch_size):
    return repr((model_config.vocab_size, tuple(mesh_shape),
                 global_batch_size))
"""


def test_cky001_signature_parity_fires(tmp_path):
    report = lint_files(tmp_path, {
        "pkg/keys.py": CKY_KEYS,
        "pkg/build.py": CKY_BUILD_PARITY_BAD,
    }, select=["CKY001"])
    symbols = {f.symbol for f in report.findings}
    assert "build_sharded_train::overlap" in symbols


def test_cky001_uncovered_knob_read_fires(tmp_path):
    report = lint_files(tmp_path, {
        "pkg/keys.py": CKY_KEYS,
        "pkg/build.py": CKY_BUILD_PARITY_BAD,
        "pkg/caller.py": CKY_READ_BAD,
    }, select=["CKY001"])
    symbols = {f.symbol for f in report.findings}
    assert "make_programs::config.overlap" in symbols


def test_cky001_covered_spellings_are_clean(tmp_path):
    report = lint_files(tmp_path, {
        "pkg/keys.py": CKY_KEYS,
        "pkg/build.py": CKY_BUILD_OK,
        "pkg/caller.py": CKY_READ_OK,
    }, select=["CKY001"])
    assert report.findings == []


def test_cky001_missing_vars_fold_fires(tmp_path):
    report = lint_files(tmp_path, {
        "pkg/keys.py": CKY_KEYS_NO_VARS,
    }, select=["CKY001"])
    symbols = {f.symbol for f in report.findings}
    assert "train_cache_key::vars" in symbols


def test_cky001_inline_suppression(tmp_path):
    report = lint_files(tmp_path, {
        "pkg/keys.py": CKY_KEYS,
        "pkg/build.py": CKY_BUILD_PARITY_BAD,
        "pkg/caller.py": CKY_READ_SUPPRESSED,
    }, select=["CKY001"])
    assert "make_programs::config.overlap" not in {
        f.symbol for f in report.findings
    }
    assert report.suppressed >= 1


def test_cky001_silent_without_key_functions(tmp_path):
    """Trees that define no cache key (fixtures, partial lints) must not
    drown in findings — the rule guards a contract, not a style."""
    report = lint_files(tmp_path, {
        "pkg/app.py": "def run(config):\n    return config.zero1\n",
    }, select=["CKY001"])
    assert report.findings == []


# -- TEL001: telemetry emit -> route -> render contract --------------------

TEL_TELEMETRY = """\
def event(name, /, duration_s=0.0, t_mono=None, **attrs):
    return (name, duration_s, attrs)

def span(name, /, **attrs):
    return name
"""

TEL_MASTER = """\
class SpeedMonitor:
    def record_fault(self, seam, kind, seconds):
        pass

class Servicer:
    def _report_telemetry(self, events):
        for name, duration_s, attrs in events:
            if name == "fault":
                self.speed_monitor.record_fault(
                    attrs.get("seam"), attrs.get("kind"), duration_s
                )
"""

TEL_WORKER_ROUTED = """\
from pkg import telemetry

def report(seam):
    telemetry.event("fault", seam=seam)
"""

TEL_WORKER_UNROUTED = """\
from pkg import telemetry

def report():
    telemetry.event("retry")
"""

TEL_WORKER_TIMED = """\
from pkg import telemetry

def report(dt):
    telemetry.event("compile", duration_s=dt)
"""

TEL_WORKER_SUPPRESSED = """\
from pkg import telemetry

def report():
    telemetry.event("retry")  # tracelint: disable=TEL001
"""

TEL_MASTER_DEAD_ROUTE = """\
class Servicer:
    def _report_telemetry(self, events):
        for name, duration_s, attrs in events:
            if name == "ghost":
                self.count += 1
"""

TEL_RENDER = """\
class Timeline:
    def bump(self, name, n=1):
        self._counters[name] = self._counters.get(name, 0) + n

    def note(self):
        self.bump("orphan")

    def render_metrics(self):
        lines = []

        def gauge(name, value, help_text="", labels=""):
            if help_text:
                lines.append(f"# HELP {name} {help_text}")
                lines.append(f"# TYPE {name} gauge")
            lines.append(f"{name}{labels} {value}")

        gauge("dlrover_good_total", 1, "a documented counter")
        gauge("dlrover_bare_total", 2)
        return lines
"""

TEL_SPEED_MONITOR_DRIFT = """\
class SpeedMonitor:
    def record_used(self, node):
        pass

    def record_orphan(self, node):
        pass

class Servicer:
    def _report_telemetry(self, events):
        for name, duration_s, attrs in events:
            if name == "used":
                self.speed_monitor.record_used(attrs["node"])
            elif name == "gone":
                self.speed_monitor.record_gone(attrs["node"])
"""


def test_tel001_unrouted_instant_event_fires(tmp_path):
    report = lint_files(tmp_path, {
        "pkg/telemetry.py": TEL_TELEMETRY,
        "pkg/master.py": TEL_MASTER,
        "pkg/worker.py": TEL_WORKER_ROUTED,
        "pkg/flaky.py": TEL_WORKER_UNROUTED,
    }, select=["TEL001"])
    symbols = {f.symbol for f in report.findings}
    assert "event::retry" in symbols
    assert "event::fault" not in symbols


def test_tel001_timed_events_are_exempt(tmp_path):
    report = lint_files(tmp_path, {
        "pkg/telemetry.py": TEL_TELEMETRY,
        "pkg/master.py": TEL_MASTER,
        "pkg/worker.py": TEL_WORKER_ROUTED,
        "pkg/timed.py": TEL_WORKER_TIMED,
    }, select=["TEL001"])
    assert report.findings == []


def test_tel001_dead_route_fires(tmp_path):
    report = lint_files(tmp_path, {
        "pkg/telemetry.py": TEL_TELEMETRY,
        "pkg/master.py": TEL_MASTER_DEAD_ROUTE,
    }, select=["TEL001"])
    symbols = {f.symbol for f in report.findings}
    assert "route::ghost" in symbols


def test_tel001_silent_without_routing_functions(tmp_path):
    """Single-file fixtures with no master in sight emit freely."""
    report = lint_files(tmp_path, {
        "pkg/telemetry.py": TEL_TELEMETRY,
        "pkg/worker.py": TEL_WORKER_UNROUTED,
    }, select=["TEL001"])
    assert report.findings == []


def test_tel001_gauge_help_and_orphan_counter(tmp_path):
    report = lint_files(tmp_path, {
        "pkg/timeline.py": TEL_RENDER,
    }, select=["TEL001"])
    symbols = {f.symbol for f in report.findings}
    assert "gauge::dlrover_bare_total" in symbols
    assert "gauge::dlrover_good_total" not in symbols
    assert "counter::orphan" in symbols


def test_tel001_speed_monitor_surface_drift(tmp_path):
    report = lint_files(tmp_path, {
        "pkg/telemetry.py": TEL_TELEMETRY,
        "pkg/master.py": TEL_SPEED_MONITOR_DRIFT,
        "pkg/worker.py": (
            "from pkg import telemetry\n\n"
            "def a():\n    telemetry.event(\"used\")\n\n"
            "def b():\n    telemetry.event(\"gone\")\n"
        ),
    }, select=["TEL001"])
    symbols = {f.symbol for f in report.findings}
    assert "speed_monitor::record_gone" in symbols
    assert "speed_monitor::orphan::record_orphan" in symbols
    assert "speed_monitor::orphan::record_used" not in symbols


def test_tel001_inline_suppression(tmp_path):
    report = lint_files(tmp_path, {
        "pkg/telemetry.py": TEL_TELEMETRY,
        "pkg/master.py": TEL_MASTER,
        "pkg/flaky.py": TEL_WORKER_SUPPRESSED,
    }, select=["TEL001"])
    assert "event::retry" not in {f.symbol for f in report.findings}
    assert report.suppressed >= 1


# -- LCK001: lockset races (CFG must-hold analysis) ------------------------

LCK_INCONSISTENT = """\
import threading

class Pump:
    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0
        self._thread = threading.Thread(target=self._run)

    def _run(self):
        while True:
            with self._lock:
                self._value += 1

    def reset(self):
        self._value = 0
"""

LCK_DISJOINT = """\
import threading

class Pump:
    def __init__(self):
        self._a_lock = threading.Lock()
        self._b_lock = threading.Lock()
        self._value = 0
        self._thread = threading.Thread(target=self._run)

    def _run(self):
        while True:
            with self._a_lock:
                self._value += 1

    def snapshot(self):
        with self._b_lock:
            return self._value
"""

# acquire()/try/finally/release() is a held lock — the lexical heuristic
# (THR001) cannot see it, the must-hold dataflow can.
LCK_TRY_FINALLY_OK = """\
import threading

class Pump:
    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0
        self._thread = threading.Thread(target=self._run)

    def _run(self):
        while True:
            self._lock.acquire()
            try:
                self._value += 1
            finally:
                self._lock.release()

    def snapshot(self):
        with self._lock:
            return self._value
"""

LCK_CONSISTENT = """\
import threading

class Pump:
    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0
        self._thread = threading.Thread(target=self._run)

    def _run(self):
        while True:
            with self._lock:
                self._value += 1

    def snapshot(self):
        with self._lock:
            return self._value
"""

LCK_SUPPRESSED = """\
import threading

class Pump:
    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0
        self._thread = threading.Thread(target=self._run)

    def _run(self):
        while True:
            with self._lock:
                self._value += 1

    def reset(self):
        self._value = 0  # tracelint: disable=LCK001
"""


def test_lck001_inconsistent_guard_fires(tmp_path):
    report = lint(tmp_path, "m.py", LCK_INCONSISTENT, select=["LCK001"])
    assert rule_ids(report) == ["LCK001"]
    assert report.findings[0].symbol == "Pump._value"
    assert "empty lockset" in report.findings[0].message


def test_lck001_disjoint_locksets_fire(tmp_path):
    report = lint(tmp_path, "m.py", LCK_DISJOINT, select=["LCK001"])
    assert rule_ids(report) == ["LCK001"]
    assert "disjoint" in report.findings[0].message
    # The lexical heuristic calls both sides "locked" and stays silent —
    # this race shape is exactly what the lockset analysis adds.
    assert rule_ids(
        lint(tmp_path, "m2.py", LCK_DISJOINT, select=["THR001"])
    ) == []


def test_lck001_try_finally_acquire_is_held(tmp_path):
    report = lint(tmp_path, "m.py", LCK_TRY_FINALLY_OK, select=["LCK001"])
    assert report.findings == []
    # ...while the lexical heuristic false-positives on the same code:
    # the motivating THR001 -> LCK001 precision delta.
    assert rule_ids(
        lint(tmp_path, "m2.py", LCK_TRY_FINALLY_OK, select=["THR001"])
    ) == ["THR001"]


def test_lck001_consistent_locking_is_clean(tmp_path):
    report = lint(tmp_path, "m.py", LCK_CONSISTENT, select=["LCK001"])
    assert report.findings == []


def test_lck001_fully_unguarded_attr_is_thr001_territory(tmp_path):
    source = LCK_INCONSISTENT.replace(
        "            with self._lock:\n                self._value += 1",
        "            self._value += 1",
    )
    report = lint(tmp_path, "m.py", source, select=["LCK001"])
    assert report.findings == []
    assert rule_ids(
        lint(tmp_path, "m2.py", source, select=["THR001"])
    ) == ["THR001"]


def test_lck001_inline_suppression(tmp_path):
    report = lint(tmp_path, "m.py", LCK_SUPPRESSED, select=["LCK001"])
    assert report.findings == []
    assert report.suppressed == 1


# -- SARIF: new rules advertised with stable indices -----------------------

def test_sarif_rule_indices_cover_new_rules(tmp_path):
    report = lint(tmp_path, "m.py", LCK_INCONSISTENT, select=None)
    sarif = json.loads(report.render_sarif())
    driver_rules = sarif["runs"][0]["tool"]["driver"]["rules"]
    ids = [r["id"] for r in driver_rules]
    assert ids == sorted(ids), "ruleIndex must follow sorted rule ids"
    for rule_id in ("CKY001", "TEL001", "LCK001"):
        assert rule_id in ids
    for result in sarif["runs"][0]["results"]:
        assert ids[result["ruleIndex"]] == result["ruleId"]
