"""Dead-node chaos test: SIGKILL one of two agents mid-training; the
survivor must detect the broken world, requeue the dead host's shards,
re-form a 1-node world, resume from checkpoint and finish.

This is the TPU counterpart of the reference's pod-kill experiments
(ref ``docs/tech_report/fault_tolerance_exps.md:145-210``) exercising the
heartbeat-death path end-to-end: master ``check_heartbeats`` ->
``_handle_node_death`` (evict from rendezvous + ``recover_tasks``) ->
survivor ``world_changed`` -> membership restart -> smaller world seals.
"""

import os
import signal
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _agent_cmd(master_addr, node_id, ckpt_dir, steps):
    return [
        sys.executable, "-m", "dlrover_tpu.run",
        "--master", master_addr,
        "--nnodes", "1:2",
        "--node-id", str(node_id),
        "--max-restarts", "3",
        "--monitor-interval", "1",
        "--heartbeat-interval", "1",
        "--checkpoint-dir", ckpt_dir,
        "--", sys.executable, os.path.join(REPO, "examples", "train_lm.py"),
        "--steps", str(steps), "--ckpt-every", "4",
        "--checkpoint-dir", ckpt_dir,
        "--layers", "1", "--d-model", "64", "--heads", "2",
        "--seq-len", "64", "--batch-size", "4",
        "--step-sleep", "0.3",
    ]


@pytest.mark.slow
def test_sigkill_one_of_two_agents_survivor_recovers(tmp_path, cpu_child_env):
    from dlrover_tpu.common.storage import CheckpointDirLayout, PosixDiskStorage
    from dlrover_tpu.master.job_master import JobMaster

    ckpt_dir = str(tmp_path / "ckpt")
    steps = 24
    master = JobMaster(
        num_nodes=2, min_nodes=1, rdzv_waiting_timeout=3.0,
        heartbeat_timeout=5.0,
    )
    master.CONTROL_LOOP_INTERVAL = 1.0
    port = master.start()
    addr = f"localhost:{port}"

    env = cpu_child_env
    env.update(
        {
            "DLROVER_TPU_SOCKET_DIR": str(tmp_path / "socks"),
            "DLROVER_TPU_JOB": f"chaos{os.getpid()}",
            "PYTHONPATH": REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
        }
    )
    env.pop("XLA_FLAGS", None)

    procs = {}
    try:
        for node_id in (0, 1):
            procs[node_id] = subprocess.Popen(
                _agent_cmd(addr, node_id, ckpt_dir, steps),
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
                start_new_session=True,  # killpg takes out agent + trainer
            )

        # Wait for the first committed checkpoint so the survivor has
        # something to resume from, then SIGKILL node 1's process group
        # (agent and trainer die silently — no failure report, no SIGTERM
        # persist; only heartbeat timeout can discover this).
        layout = CheckpointDirLayout(ckpt_dir)
        storage = PosixDiskStorage()
        deadline = time.monotonic() + 240
        while layout.latest_step(storage) < 4:
            assert time.monotonic() < deadline, "no checkpoint within 240s"
            assert procs[0].poll() is None, procs[0].communicate()[0][-3000:]
            assert procs[1].poll() is None, "agent 1 died prematurely"
            time.sleep(0.5)
        os.killpg(os.getpgid(procs[1].pid), signal.SIGKILL)
        procs[1].wait(timeout=10)

        out, _ = procs[0].communicate(timeout=240)
        assert procs[0].returncode == 0, out[-5000:]
        assert "membership changed" in out
        assert "resumed from checkpoint at step" in out
        assert layout.latest_step(storage) == steps

        # The master declared node 1 dead and relaunched (noop launcher ->
        # PENDING); its unfinished shards were requeued and completed by the
        # survivor (exhausted task queue lets the trainer reach `steps`).
        assert master.node_manager.statuses()[1] in ("pending", "dead")
    finally:
        for p in procs.values():
            if p.poll() is None:
                try:
                    os.killpg(os.getpgid(p.pid), signal.SIGKILL)
                except ProcessLookupError:
                    pass
        master.stop()
