"""Mesh runtime tests (dlrover_tpu/runtime/mesh.py)."""

import jax
import pytest

from dlrover_tpu.runtime import mesh as mesh_lib
from dlrover_tpu.runtime.mesh import MESH_AXES, ParallelConfig, build_mesh


def test_eight_cpu_devices():
    assert jax.device_count() == 8


def test_parallel_config_sizes():
    cfg = ParallelConfig(tensor=2, fsdp=2)
    sizes = cfg.sizes(8)
    assert sizes["tensor"] == 2 and sizes["fsdp"] == 2 and sizes["data"] == 2


def test_parallel_config_rejects_bad_sizes():
    with pytest.raises(ValueError):
        ParallelConfig(tensor=3).sizes(8)
    with pytest.raises(ValueError):
        ParallelConfig(data=2, tensor=2).sizes(8)


def test_build_mesh_axes_order():
    mesh = build_mesh(ParallelConfig(tensor=2, pipe=2, data=2))
    assert mesh.axis_names == MESH_AXES
    assert mesh.devices.size == 8
    assert mesh.shape["tensor"] == 2
    assert mesh.shape["pipe"] == 2
    assert mesh.shape["data"] == 2


def test_factor_devices():
    sizes = mesh_lib.factor_devices(8)
    total = 1
    for v in sizes.values():
        total *= v
    assert total == 8


def test_slice_topology():
    info = mesh_lib.slice_topology()
    assert info["num_devices"] == 8
    assert info["platform"] == "cpu"
