"""Stack-dump collector (ref ``datacollector/cuda_log_collector.py``):
the agent must be able to ask a live trainer WHERE it is stuck."""

import os
import subprocess
import sys
import time

import pytest

from dlrover_tpu.agent.stack_collector import (
    ENV_STACK_FILE,
    collect_stacks,
    install_stack_dump_handler,
)


@pytest.mark.slow  # spawns a python subprocess and polls it for seconds
def test_collect_stacks_from_live_process(tmp_path):
    path = str(tmp_path / "stacks.txt")
    child = subprocess.Popen(
        [sys.executable, "-c", (
            "import time\n"
            "from dlrover_tpu.agent.stack_collector import "
            "install_stack_dump_handler\n"
            "install_stack_dump_handler()\n"
            "def deep_in_training_step():\n"
            "    time.sleep(60)\n"
            "deep_in_training_step()\n"
        )],
        env={**os.environ, ENV_STACK_FILE: path,
             "PYTHONPATH": os.getcwd()},
    )
    try:
        deadline = time.monotonic() + 10
        while not os.path.exists(path) and time.monotonic() < deadline:
            time.sleep(0.05)
        time.sleep(0.3)  # let the handler registration land
        stacks = collect_stacks(child.pid, path, timeout_s=5.0)
        assert "deep_in_training_step" in stacks, stacks
        # a second collection reads only the NEW dump
        stacks2 = collect_stacks(child.pid, path, timeout_s=5.0)
        assert "deep_in_training_step" in stacks2
    finally:
        child.kill()
        child.wait()


def test_collect_stacks_dead_process(tmp_path):
    path = str(tmp_path / "stacks.txt")
    child = subprocess.Popen([sys.executable, "-c", "pass"])
    child.wait()
    assert collect_stacks(child.pid, path, timeout_s=0.5) == ""


def test_install_without_env_is_noop(monkeypatch):
    monkeypatch.delenv(ENV_STACK_FILE, raising=False)
    assert install_stack_dump_handler() is None
