"""Tensor-parallel serving: sharded decode programs, disaggregated
prefill, speculative decoding.

Tier-1 coverage for the PR-17 serving plane:

1. the TP mesh — fold rule (largest divisor that fits), config
   divisibility validation, serve-key sensitivity to the new knobs;
2. TP decode — greedy token parity vs tp=1 (fp32 activations make the
   argmax decisive, so parity is bitwise), per-device KV-pool bytes
   shrinking with the fold, mid-serve re-fold to a seen width hitting
   the program memo (zero retrace);
3. disaggregated prefill — a prefill-role replica streams KV page rows
   to a decode-role replica through the fleet with token parity against
   a colocated engine, and role guards reject the wrong traffic;
4. speculative decoding — a draft sharing the target's weights accepts
   nearly everything, a random draft accepts little but NEVER changes
   the emitted greedy stream, sampled rows complete, and the γ bounds /
   verify-write headroom are enforced at submit time;
5. scale policy — low-confidence p95 (few completed requests) neither
   triggers a breach scale-out nor licenses a scale-in; the prefill pool
   scales on its own backlog signal.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlrover_tpu.master.auto_scaler import ServeScalePolicy
from dlrover_tpu.models.transformer import TransformerConfig, TransformerLM
from dlrover_tpu.rl.generation import SamplingParams
from dlrover_tpu.runtime.compile_cache import serve_cache_key
from dlrover_tpu.serving import ReplicaFleet, Request, ServingEngine
from dlrover_tpu.serving.engine import _nearest_rank
from dlrover_tpu.serving.tp import (
    ServeTPMesh,
    build_tp_mesh,
    fold_width,
    validate_tp_config,
)
from dlrover_tpu.trainer import train_lib

VOCAB, SEQ = 64, 32
BUCKETS = (8,)
SLOTS = 2


@pytest.fixture(scope="module")
def setup():
    # fp32 activations: greedy parity across TP widths is only bitwise
    # when the top-2 logit gap exceeds the reduction reassociation
    # error, which bf16 does not guarantee (tools/serve_bench.py has the
    # same note for the drill).
    config = TransformerConfig(
        vocab_size=VOCAB, d_model=32, num_heads=4, num_layers=2,
        d_ff=64, max_seq_len=SEQ, dtype=jnp.float32,
    )
    params = TransformerLM(config).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32)
    )["params"]
    return config, params


def _engine(setup, **kw):
    config, params = setup
    kw.setdefault("slots", SLOTS)
    kw.setdefault("buckets", BUCKETS)
    kw.setdefault("seed", 0)
    return ServingEngine(config, params, **kw)


def _reqs(n=4, new=6, temp=0.0):
    out = []
    for i in range(n):
        prompt = np.asarray(
            jax.random.randint(jax.random.PRNGKey(100 + i),
                               (5 + i % 4,), 1, VOCAB),
            np.int32,
        )
        out.append(Request(
            uid=f"r{i}", prompt=prompt,
            sampling=SamplingParams(max_new_tokens=new, temperature=temp),
        ))
    return out


def _tokens(results):
    return {u: r.tokens.tolist() for u, r in results.items()}


# -- TP mesh units ------------------------------------------------------------


def test_fold_width_largest_fitting_divisor():
    assert fold_width(4, 8) == 4
    assert fold_width(4, 3) == 2
    assert fold_width(4, 1) == 1
    assert fold_width(6, 4) == 3
    with pytest.raises(ValueError):
        fold_width(0, 4)


def test_validate_tp_config_names_failing_dim(setup):
    config, _ = setup
    validate_tp_config(config, 2)
    validate_tp_config(config, 4)
    with pytest.raises(ValueError, match="num_heads"):
        validate_tp_config(config, 8)


def test_tp_mesh_fold_preserves_logical_shape():
    tp = build_tp_mesh(4)
    assert isinstance(tp, ServeTPMesh)
    assert tp.logical_tp == 4 and tp.physical_tp == 4
    folded = tp.fold_to(2)
    assert folded.logical_tp == 4 and folded.physical_tp == 2


def test_serve_cache_key_covers_tp_and_spec_knobs(setup):
    # Single witness; knob-by-knob coverage of serve_cache_key is
    # enforced statically by tracelint CKY001 (tests/test_lint_gate.py).
    config, _ = setup

    def key(**kw):
        return serve_cache_key(config, slots=SLOTS, buckets=BUCKETS,
                               max_top_k=64, **kw)

    base = key()
    assert key() == base
    assert key(tp=(2, 2)) != key(tp=(2, 1))  # re-fold = new programs


# -- TP decode parity + sharding ----------------------------------------------


def test_tp2_greedy_parity_and_kv_bytes_shrink(setup):
    plain = _engine(setup)
    baseline = _tokens(plain.run(_reqs()))
    assert all(len(t) == 6 for t in baseline.values())
    tp2 = _engine(setup, tp=2, tp_devices=2)
    assert _tokens(tp2.run(_reqs())) == baseline
    # The KV pool is sharded on the heads axis: per-device bytes halve
    # (up to the replicated scalar rows).
    assert tp2.kv_device_bytes() < plain.kv_device_bytes()
    assert tp2.kv_device_bytes() <= plain.kv_device_bytes() / 2 * 1.15


@pytest.mark.slow  # one more TP fold to compile (~10s on 1 core)
def test_tp4_greedy_parity(setup):
    plain = _engine(setup)
    baseline = _tokens(plain.run(_reqs()))
    tp4 = _engine(setup, tp=4, tp_devices=4)
    assert _tokens(tp4.run(_reqs())) == baseline
    assert tp4.kv_device_bytes() < plain.kv_device_bytes() / 2


@pytest.mark.slow  # compiles the tp=4 and tp=2 folds (~20s on 1 core)
def test_fold_tp_mid_serve_then_back_zero_retrace(setup):
    engine = _engine(setup, tp=4, tp_devices=4)
    reqs = _reqs(n=6, new=8)
    for r in reqs[:3]:
        engine.submit(r)
    engine.step()
    # Cold fold 4→2 mid-serve: live KV rows re-place onto the new fold
    # and decoding continues — requests land complete.
    engine.fold_tp(2)
    engine.drain()
    for r in reqs[3:]:
        engine.submit(r)
    engine.step()
    # Warm fold back to a seen width must hit the program memo: zero
    # traces of any serve program while requests are still in flight.
    keys = ("serve_prefill", "serve_insert", "serve_decode")
    before = {k: train_lib.TRACE_COUNTS[k] for k in keys}
    engine.fold_tp(4)
    results = engine.drain()
    assert sorted(results) == sorted(r.uid for r in reqs)
    assert all(train_lib.TRACE_COUNTS[k] == before[k] for k in keys)
    # And the folded streams match the unfolded greedy baseline.
    baseline = _tokens(_engine(setup).run(_reqs(n=6, new=8)))
    assert _tokens(results) == baseline


# -- disaggregated prefill ----------------------------------------------------


def test_page_streaming_parity_vs_colocated(setup):
    colocated = _tokens(_engine(setup).run(_reqs()))
    fleet = ReplicaFleet(min_replicas=1)
    pre = _engine(setup, role="prefill")
    dec = _engine(setup, role="decode", seed=0)
    fleet.add_replica(pre)
    fleet.add_replica(dec)
    for r in _reqs():
        fleet.submit(r)
    for _ in range(200):
        if fleet.pending() == 0:
            break
        fleet.step()
    assert fleet.pending() == 0
    assert _tokens(fleet.results) == colocated
    stats = fleet.stats()
    assert stats["pages_streamed"] == len(colocated)
    assert stats["page_bytes_streamed"] > 0
    assert dec.stats()["pages_in"] == len(colocated)
    assert pre.stats()["pages_out"] == len(colocated)


def test_role_guards_reject_wrong_traffic(setup):
    dec = _engine(setup, role="decode")
    with pytest.raises(ValueError, match="decode"):
        dec.submit(_reqs(n=1)[0])
    pre = _engine(setup, role="prefill")
    pre.submit(_reqs(n=1)[0])
    assert pre.step() >= 0
    assert len(pre.outbox) == 1
    with pytest.raises(ValueError, match="prefill"):
        pre.insert_page(pre.outbox[0])


# -- speculative decoding -----------------------------------------------------


def test_spec_self_draft_accepts_nearly_everything(setup):
    config, params = setup
    plain = _tokens(_engine(setup).run(_reqs(new=8)))
    spec = _engine(setup, draft_config=config, draft_params=params,
                   spec_tokens=3)
    assert _tokens(spec.run(_reqs(new=8))) == plain
    stats = spec.stats()
    assert stats["spec_proposed"] > 0
    # The draft IS the target: fp32 keeps the γ+1-wide verify pass and
    # the incremental draft pass argmax-identical, so every rejection is
    # commit truncation at the max_new_tokens boundary, not a mismatch
    # (the last verify proposes γ but the request only has room for
    # fewer).
    assert stats["spec_accept_rate"] >= 0.8


def test_spec_random_draft_never_changes_the_stream(setup):
    config, params = setup
    draft_config = dataclasses.replace(config, num_layers=1)
    draft_params = TransformerLM(draft_config).init(
        jax.random.PRNGKey(7), jnp.zeros((1, 4), jnp.int32)
    )["params"]
    plain = _tokens(_engine(setup).run(_reqs(new=8)))
    spec = _engine(setup, draft_config=draft_config,
                   draft_params=draft_params, spec_tokens=3)
    # Rejection sampling's whole contract: a useless draft costs speed,
    # never correctness.
    assert _tokens(spec.run(_reqs(new=8))) == plain
    stats = spec.stats()
    assert stats["spec_proposed"] > 0
    assert stats["spec_accepted"] <= stats["spec_proposed"]
    assert stats["spec_accept_rate"] < 0.8


def test_spec_sampled_rows_complete(setup):
    config, params = setup
    spec = _engine(setup, draft_config=config, draft_params=params,
                   spec_tokens=3, seed=11)
    results = spec.run(_reqs(new=7, temp=0.8))
    assert len(results) == 4
    assert all(len(r.tokens) == 7 for r in results.values())
    assert all(np.all(r.tokens < VOCAB) for r in results.values())


def test_spec_headroom_enforced_at_submit(setup):
    config, params = setup
    plain = _engine(setup)
    spec = _engine(setup, draft_config=config, draft_params=params,
                   spec_tokens=3)
    prompt = np.arange(1, 6, dtype=np.int32)
    # bucket 8 + 22 new fits max_seq_len 32 plain, but not with the
    # γ=3 verify-write headroom on top.
    fits_plain = Request(
        uid="edge", prompt=prompt,
        sampling=SamplingParams(max_new_tokens=22),
    )
    plain.submit(fits_plain)
    with pytest.raises(ValueError, match="spec headroom"):
        spec.submit(fits_plain)


def test_spec_tokens_bounds(setup):
    config, params = setup
    for bad in (0, 15):
        with pytest.raises(ValueError, match="spec_tokens"):
            _engine(setup, draft_config=config, draft_params=params,
                    spec_tokens=bad)


# -- quantile confidence + scale policy ---------------------------------------


def test_nearest_rank_quantile():
    values = sorted(float(v) for v in range(1, 11))
    assert _nearest_rank(values, 0.50) == 5.0
    assert _nearest_rank(values, 0.95) == 10.0
    assert _nearest_rank([3.0], 0.95) == 3.0
    assert _nearest_rank([2.0, 4.0], 0.95) == 4.0


def test_maybe_scale_ignores_low_confidence_p95(setup):
    fleet = ReplicaFleet(spawn=lambda: _engine(setup, seed=9))
    fleet.add_replica(_engine(setup))
    policy = ServeScalePolicy(slo_p95_s=1.0, min_qps=0.0, min_samples=8)
    # p95 breach backed by 2 completions: noise, not a signal.
    shaky = dict(replicas=1.0, qps=5.0, p95_s=2.0, occupancy=0.2,
                 p95_n=2.0)
    fleet.stats = lambda: shaky  # type: ignore[method-assign]
    assert fleet.maybe_scale(policy) is None
    # Occupancy is always well-sampled: it still scales out.
    hot = dict(shaky, occupancy=0.95)
    fleet.stats = lambda: hot  # type: ignore[method-assign]
    assert fleet.maybe_scale(policy) == "out"
    # An unconfident LOW p95 cannot license a scale-in either.
    idle = dict(replicas=2.0, qps=5.0, p95_s=0.1, occupancy=0.05,
                p95_n=2.0)
    fleet.stats = lambda: idle  # type: ignore[method-assign]
    assert fleet.maybe_scale(policy) is None
    confident = dict(idle, p95_n=50.0)
    fleet.stats = lambda: confident  # type: ignore[method-assign]
    assert fleet.maybe_scale(policy) == "in"


def test_maybe_scale_prefill_pool_on_backlog(setup):
    fleet = ReplicaFleet(
        spawn=lambda: _engine(setup, seed=9),
        spawn_prefill=lambda: _engine(setup, role="prefill", seed=10),
    )
    fleet.add_replica(_engine(setup, role="prefill"))
    fleet.add_replica(_engine(setup, role="decode"))
    policy = ServeScalePolicy(min_qps=0.0, prefill_backlog_high=4.0)
    backed_up = dict(replicas=2.0, qps=5.0, p95_s=0.1, occupancy=0.2,
                     p95_n=50.0, prefill_replicas=1.0,
                     prefill_backlog=9.0)
    fleet.stats = lambda: backed_up  # type: ignore[method-assign]
    assert fleet.maybe_scale(policy) == "out"
    assert sum(
        1 for r in fleet._replicas.values()
        if getattr(r.engine, "role", "mixed") == "prefill"
    ) == 2
