"""Observation-driven auto-scaling + cloud NodeLauncher.

VERDICT r3 items #2/#3: scale decisions must come from the observed
throughput history (no manual ``set_target``), and node actuation must
work against a (faked) cloud TPU-VM API the way the reference's pod
scaler works against a mocked k8s client
(``dlrover/python/master/scaler/pod_scaler.py`` +
``tests/test_utils.py:200-295``).
"""

import time


from dlrover_tpu.master.auto_scaler import JobAutoScaler
from dlrover_tpu.master.brain import Observation, RunningJobOptimizer
from dlrover_tpu.master.cloud_launcher import (
    CloudNodeLauncher,
    FakeTpuVmClient,
    TpuVmState,
)
from dlrover_tpu.master.job_master import JobMaster
from dlrover_tpu.master.node_manager import NodeManager, NodeStatus
from dlrover_tpu.master.speed_monitor import SpeedMonitor


# ---------------------------------------------------------------------------
# RunningJobOptimizer (pure policy)
# ---------------------------------------------------------------------------


def _feed(opt, nodes, speeds):
    for s in speeds:
        opt.observe(Observation(num_nodes=nodes, speed=s))


def test_optimizer_explores_up_after_stable_readings():
    opt = RunningJobOptimizer(patience=3)
    _feed(opt, 2, [10.0, 10.5, 10.2])
    plan = opt.recommend(current_nodes=2, min_nodes=1, max_nodes=4,
                         node_unit=1)
    assert plan.num_nodes == 3
    assert "exploring" in plan.reason


def test_optimizer_retreats_when_uplift_too_small():
    opt = RunningJobOptimizer(uplift_threshold=1.1, patience=3)
    _feed(opt, 2, [10.0, 10.0, 10.0])
    _feed(opt, 3, [10.4, 10.5, 10.4])  # +5% for +50% nodes: wasted unit
    plan = opt.recommend(current_nodes=3, min_nodes=1, max_nodes=4)
    assert plan.num_nodes == 2
    assert "wasted" in plan.reason


def test_optimizer_retreat_gated_on_samples():
    """Right after an explore step, one contaminated reading must NOT
    retreat — the larger world would be locked out permanently."""
    opt = RunningJobOptimizer(uplift_threshold=1.1, patience=3)
    _feed(opt, 2, [10.0, 10.0, 10.0])
    _feed(opt, 3, [6.0])  # warmup-depressed first sample at the new size
    plan = opt.recommend(current_nodes=3, min_nodes=1, max_nodes=4)
    assert plan.num_nodes == 3  # keep observing, don't retreat yet


def test_optimizer_keeps_config_when_uplift_real():
    opt = RunningJobOptimizer(uplift_threshold=1.1, patience=3)
    _feed(opt, 2, [10.0, 10.0, 10.0])
    _feed(opt, 3, [14.5, 14.8, 14.6])
    _feed(opt, 4, [19.0, 19.5, 19.2])  # ceiling reached, scaling pays
    plan = opt.recommend(current_nodes=4, min_nodes=1, max_nodes=4)
    assert plan.num_nodes == 4


def test_optimizer_flags_sustained_degradation():
    opt = RunningJobOptimizer(degrade_threshold=0.7, patience=2)
    _feed(opt, 4, [20.0, 20.0, 20.0])
    _feed(opt, 4, [5.0, 5.0])  # two consecutive collapsed OBSERVATIONS
    plan = opt.recommend(4, 1, 4)
    assert plan.num_nodes == 4 and "degraded" in plan.reason
    # a healthy observation clears the streak
    _feed(opt, 4, [19.5])
    plan = opt.recommend(4, 1, 4)
    assert "degraded" not in plan.reason


def test_optimizer_reexplores_stale_size():
    """VERDICT r4 weak #4: a size measured once during a degraded window
    must not be locked out forever — once its samples exceed the
    staleness bound it becomes explorable again."""
    opt = RunningJobOptimizer(patience=3, stale_after_s=100.0)
    old = time.time() - 500.0  # well past the staleness bound
    # Size 3 was measured (badly, during some degraded window) long ago.
    opt.observe(Observation(num_nodes=3, speed=4.0, timestamp=old))
    # Fresh, stable readings at the current size 2.
    _feed(opt, 2, [10.0, 10.1, 10.0])
    plan = opt.recommend(current_nodes=2, min_nodes=1, max_nodes=4)
    assert plan.num_nodes == 3
    assert "stale" in plan.reason


def test_optimizer_fresh_measured_size_not_reexplored():
    opt = RunningJobOptimizer(patience=3, stale_after_s=100.0)
    _feed(opt, 3, [4.0, 4.1, 4.0])  # fresh samples: 3 genuinely loses
    _feed(opt, 2, [10.0, 10.1, 10.0])
    plan = opt.recommend(current_nodes=2, min_nodes=1, max_nodes=4)
    assert plan.num_nodes == 2  # keep the better size; no explore churn


# ---------------------------------------------------------------------------
# JobAutoScaler integration: plans from observation, no set_target
# ---------------------------------------------------------------------------


class RecordingLauncher:
    def __init__(self):
        self.launched, self.deleted = [], []

    def launch(self, node_id):
        self.launched.append(node_id)

    def delete(self, node_id):
        self.deleted.append(node_id)


def test_scaler_retires_node_from_observation_only():
    """Degenerate uplift observed -> the brain recommends the smaller
    world -> a retire ScalePlan, with no manual set_target anywhere."""
    launcher = RecordingLauncher()
    nm = NodeManager(num_nodes=3, launcher=launcher)
    for n in range(3):
        nm.report_event(n, "started")
    sm = SpeedMonitor()
    opt = RunningJobOptimizer(uplift_threshold=1.1)
    scaler = JobAutoScaler(
        nm, sm, min_nodes=1, max_nodes=3, cooldown_s=0.0,
        optimizer=opt, optimize_interval_s=0.0,
    )
    # History: 2 nodes did ~10 steps/s; the present 3-node world does ~10.3
    # (enough samples at 3 to clear the retreat's warmup gate).
    _feed(opt, 2, [10.0, 10.0, 10.0])
    _feed(opt, 3, [10.3, 10.3])
    now = time.time()
    for i in range(6):
        sm.collect_global_step(i + 1, timestamp=now + i, tokens=100)
    plan = scaler.step()
    assert plan is not None, "expected an observation-driven plan"
    assert plan.delete == [2]
    assert launcher.deleted == [2]
    assert scaler.target == 2


def test_scaler_dead_node_repair_needs_no_target():
    launcher = RecordingLauncher()
    nm = NodeManager(num_nodes=2, launcher=launcher)
    for n in range(2):
        nm.report_event(n, "started")
    scaler = JobAutoScaler(
        nm, SpeedMonitor(), min_nodes=1, max_nodes=2, cooldown_s=0.0,
        optimizer=RunningJobOptimizer(), optimize_interval_s=3600.0,
    )
    nm._nodes[1].status = NodeStatus.DEAD
    plan = scaler.step()
    assert plan is not None and plan.launch == [1]
    assert launcher.launched == [1]


# ---------------------------------------------------------------------------
# Cloud launcher against the fake TPU-VM API
# ---------------------------------------------------------------------------


def _drain(launcher, timeout=5.0):
    deadline = time.monotonic() + timeout
    while not launcher._queue.empty() and time.monotonic() < deadline:
        time.sleep(0.02)
    time.sleep(0.1)  # let the in-flight create finish


def test_cloud_launch_join_retire_cycle():
    client = FakeTpuVmClient()
    launcher = CloudNodeLauncher(client, job_name="job",
                                 master_addr="10.0.0.2:50051")
    master = JobMaster(num_nodes=2, launcher=launcher, auto_scale=True,
                       min_nodes=1, heartbeat_timeout=3600.0)
    try:
        nm = master.node_manager
        # initial creation through the seam (the operator-submit path)
        master.bootstrap_nodes()
        _drain(launcher)
        assert sorted(client.create_calls) == ["job-worker-0", "job-worker-1"]
        states = launcher.reconcile()
        assert states == {0: TpuVmState.READY, 1: TpuVmState.READY}
        meta = client.get_node("job-worker-0")["metadata"]
        assert meta["dlrover-master-addr"] == "10.0.0.2:50051"
        assert meta["dlrover-node-id"] == "0"

        # the agents on the fresh VMs join the rendezvous
        elastic = list(master.rdzv_managers.values())[0]
        for n in range(2):
            nm.report_event(n, "started")
            elastic.join_rendezvous(n, 1)
        _round, _group, world = elastic.get_comm_world(0)
        assert sorted(world) == [0, 1]

        # scale down: retire the highest id through the scaler path
        master.auto_scaler.set_target(1, reason="test")
        plan = master.auto_scaler.step()
        assert plan is not None and plan.delete == [1]
        assert client.delete_calls == ["job-worker-1"]
        # survivor's world is broken so it re-forms without the retiree
        assert 1 not in elastic._alive_nodes
    finally:
        master.stop()
        launcher.shutdown()


def test_cloud_preemption_reconciles_to_node_death_and_relaunch():
    client = FakeTpuVmClient()
    launcher = CloudNodeLauncher(client, job_name="job")
    master = JobMaster(num_nodes=2, launcher=launcher, auto_scale=True,
                       heartbeat_timeout=3600.0)
    try:
        nm = master.node_manager
        master.bootstrap_nodes()
        _drain(launcher)
        for n in range(2):
            nm.report_event(n, "started")

        client.preempt("job-worker-1")
        master._reconcile_cloud()
        # death handling ran: the node transitioned and a replacement VM
        # create was enqueued (budget-limited relaunch)
        _drain(launcher)
        assert client.create_calls.count("job-worker-1") >= 2
        # the preempted VM was cleared before the re-create
        assert client.get_node("job-worker-1")["state"] in (
            TpuVmState.CREATING, TpuVmState.READY
        )
    finally:
        master.stop()
        launcher.shutdown()


def test_pending_node_preempted_before_startup_is_failed():
    """A VM preempted after its create landed but before the agent's
    first heartbeat must not leave the node PENDING forever (ADVICE r4:
    reconcile previously only handled RUNNING nodes).  The generation
    check distinguishes this from the stale VM a relaunch is replacing."""
    client = FakeTpuVmClient()
    launcher = CloudNodeLauncher(client, job_name="job")
    launcher.LANDED_SETTLE_S = 0.0  # no cloud list-cache lag in the fake
    master = JobMaster(num_nodes=1, launcher=launcher, auto_scale=True,
                       heartbeat_timeout=3600.0)
    try:
        master.bootstrap_nodes()
        _drain(launcher)
        assert launcher.vm_is_current(0)
        # Node 0 is still PENDING (no heartbeat yet) when its VM dies.
        assert master.node_manager.statuses()[0] == "pending"
        client.preempt("job-worker-0")
        # PENDING_DEAD_TICKS=2: the first observation arms the debounce,
        # the second fires it.
        master._reconcile_cloud()
        assert master.node_manager.statuses()[0] == "pending"
        master._reconcile_cloud()
        # The failure consumed relaunch budget and a replacement create
        # was enqueued; the node did NOT silently stay PENDING forever.
        _drain(launcher)
        assert client.create_calls.count("job-worker-0") >= 2
        assert client.get_node("job-worker-0")["state"] in (
            TpuVmState.CREATING, TpuVmState.READY
        )
        # While the replacement's create is the newest generation and has
        # landed, a second reconcile of a now-healthy VM does nothing.
        statuses_before = dict(master.node_manager.statuses())
        master._reconcile_cloud()
        assert master.node_manager.statuses() == statuses_before
    finally:
        master.stop()
        launcher.shutdown()


def test_stale_dead_vm_of_relaunching_node_is_ignored():
    """The old behavior the generation check must preserve: a PENDING
    node whose dead VM is the one a relaunch is still replacing must not
    be re-failed every reconcile tick (that would burn the relaunch
    budget on one preemption)."""
    client = FakeTpuVmClient()
    launcher = CloudNodeLauncher(client, job_name="job")
    master = JobMaster(num_nodes=1, launcher=launcher, auto_scale=True,
                       heartbeat_timeout=3600.0)
    try:
        master.bootstrap_nodes()
        _drain(launcher)
        # Simulate: node relaunch just issued (generation bumped, create
        # not yet landed) while the dead old VM still lingers in list().
        client.preempt("job-worker-0")
        with launcher._wanted_mu:
            launcher._generation[0] += 1  # newest launch still in flight
        relaunches_before = master.node_manager.ensure_node(0).relaunch_count
        master._reconcile_cloud()
        master._reconcile_cloud()
        master._reconcile_cloud()
        assert not launcher.vm_is_current(0)
        assert master.node_manager.ensure_node(0).relaunch_count == (
            relaunches_before
        )
    finally:
        master.stop()
        launcher.shutdown()


def test_cloud_create_retry_then_gives_up_into_hook():
    client = FakeTpuVmClient()
    failed = []
    launcher = CloudNodeLauncher(
        client, job_name="job",
        node_failed_hook=lambda nid, why: failed.append((nid, why)),
    )
    launcher.RETRY_BACKOFF_S = 0.01
    try:
        client.fail_next(2)  # transient stockout: succeeds on 3rd try
        launcher.launch(0)
        _drain(launcher)
        assert client.get_node("job-worker-0")["state"] == TpuVmState.READY
        assert not failed

        client.fail_next(10)  # permanent stockout: budget exhausted
        launcher.launch(1)
        deadline = time.monotonic() + 5
        while not failed and time.monotonic() < deadline:
            time.sleep(0.02)
        assert failed and failed[0][0] == 1
        assert "RESOURCE_EXHAUSTED" in failed[0][1]
    finally:
        launcher.shutdown()


def test_master_control_loop_scales_from_observation():
    """Full wiring: the live master control loop observes a degenerate
    3rd node and retires it — no set_target, no operator input (VERDICT
    r3 #2 done-criterion)."""
    launcher = RecordingLauncher()
    master = JobMaster(
        num_nodes=3, min_nodes=1, launcher=launcher,
        heartbeat_timeout=3600.0, optimize_interval_s=0.2,
    )
    master.CONTROL_LOOP_INTERVAL = 0.1
    assert master.auto_scaler.optimizer is not None  # elastic range => brain
    master.auto_scaler.cooldown_s = 0.0
    try:
        for n in range(3):
            master.node_manager.report_event(n, "started")
        # History the brain can see: 2 nodes used to deliver the same speed.
        _feed(master.auto_scaler.optimizer, 2, [10.0, 10.0, 10.0])
        master.start()
        now = time.time()
        deadline = time.monotonic() + 10
        step = 0
        while time.monotonic() < deadline and not launcher.deleted:
            step += 1
            master.speed_monitor.collect_global_step(
                step, timestamp=now + step, tokens=100
            )
            time.sleep(0.05)
        assert launcher.deleted == [2], "control loop never retired node 2"
        assert master.auto_scaler.target == 2
        assert any(
            "brain" in p.reason or "wasted" in p.reason
            for p in master.auto_scaler.plans
        ) or master.auto_scaler.plans
    finally:
        master.stop()


def test_persistent_stockout_fails_job_instead_of_wedging():
    """Creation give-ups flow back through node_failed_hook into the
    relaunch budget: a permanent stockout ends the job instead of leaving
    a phantom PENDING node blocking the rendezvous forever."""
    client = FakeTpuVmClient()
    client.fail_next(10**6)
    launcher = CloudNodeLauncher(client, job_name="job")
    launcher.RETRY_BACKOFF_S = 0.01
    master = JobMaster(num_nodes=1, launcher=launcher, max_relaunches=2,
                       heartbeat_timeout=3600.0)
    try:
        assert launcher.node_failed_hook is not None  # wired by the master
        master.bootstrap_nodes()
        deadline = time.monotonic() + 10
        while not master.node_manager.job_failed and (
            time.monotonic() < deadline
        ):
            time.sleep(0.05)
        assert master.node_manager.job_failed
        assert "restarts" in master.node_manager.job_failure_reason or (
            "exceeded" in master.node_manager.job_failure_reason
        )
    finally:
        master.stop()
        launcher.shutdown()
